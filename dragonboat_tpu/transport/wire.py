"""Binary wire codec for the TCP transport.

reference: the reference serializes raftpb protobufs onto a framed TCP
stream (internal/transport/tcp.go [U]).  This codec is a hand-rolled
positional binary format (length-prefixed, little-endian, crc-framed by
the transport) rather than pickle: wire input is untrusted and must
never be able to execute code or allocate unboundedly on decode.

Frame layout (transport level, see tcp.py):
    magic  u32  = 0x54524654 ("TRFT")
    kind   u8   (1 = MessageBatch, 2 = Chunk; the 0x80 bit flags a
                 zlib-compressed payload — crc/length cover the bytes
                 as sent, i.e. the compressed form)
    length u32  payload byte length
    crc    u32  zlib.crc32 of payload
    payload
"""
from __future__ import annotations

import struct
import zlib
from io import BytesIO
from typing import Tuple

from ..pb import (
    Chunk,
    CompressionType,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    MASK64,
    MESSAGE_BATCH_BIN_VER,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotFile,
)

MAGIC = 0x54524654
KIND_BATCH = 1
KIND_CHUNK = 2
# resumable snapshot streams (docs/BIGSTATE.md): a reconnecting sender
# asks the receiver for its receive cursor before re-streaming.  The
# query payload is an encoded data-less Chunk carrying the stream
# identity; the response is one little-endian u64 (the next chunk
# offset the receiver needs, 0 = restart).  Unknown kinds close the
# connection on OLD receivers, which the sender treats as cursor 0 —
# rolling upgrades degrade to restart-from-zero, never to corruption.
KIND_RESUME_QUERY = 3
KIND_RESUME_RESP = 4
# frame-kind flag: payload is zlib-compressed (wire entry compression —
# reference: EntryCompression on replicated batches [U]; ours is adaptive)
KIND_COMPRESSED = 0x80
WIRE_COMPRESS_THRESHOLD = 1024

# decode-side sanity bounds (wire input is untrusted)
MAX_PAYLOAD = 256 * 1024 * 1024
MAX_ITEMS = 1 << 20

# all protocol integers are uint64, like the reference's raftpb (session
# series ids use the top of the range, e.g. SERIES_ID_REGISTER)
_u64 = struct.Struct("<Q")
_u32 = struct.Struct("<I")
_u8 = struct.Struct("<B")


class WireError(Exception):
    """Malformed or out-of-bounds wire data."""


def maybe_compress(
    kind: int,
    payload: bytes,
    flag: int,
    threshold: int,
    max_out: int = MAX_PAYLOAD,
):
    """Adaptive compression shared by the TCP framing and the tan WAL:
    payloads over ``threshold`` that actually shrink get ``flag`` OR'd
    into the kind byte (reference: EntryCompression [U]).

    Never compresses past ``max_out``, the decode side's
    bounded_decompress limit — a compressed payload that inflates beyond
    it would encode fine and then fail on every decode."""
    if threshold <= len(payload) <= max_out:
        z = zlib.compress(payload, 1)  # speed level: hot paths
        if len(z) < len(payload):
            return kind | flag, z
    return kind, payload


def bounded_decompress(payload: bytes, max_out: int) -> bytes:
    """Strict inverse of maybe_compress's compressed arm: bounded
    allocation (zlib-bomb safe) and no trailing bytes tolerated."""
    try:
        d = zlib.decompressobj()
        out = d.decompress(payload, max_out + 1)
    except zlib.error as e:
        raise WireError(f"bad compressed payload: {e}")
    if len(out) > max_out or not d.eof:
        raise WireError("decompressed payload too large")
    if d.unused_data:
        raise WireError("trailing bytes after compressed payload")
    return out


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------
def _wu64(b: BytesIO, v: int) -> None:
    # mask, don't raise: uint64 wraparound parity (pb.MASK64 policy)
    b.write(_u64.pack(v & MASK64))


def _wu32(b: BytesIO, v: int) -> None:
    b.write(_u32.pack(v))


def _wu8(b: BytesIO, v: int) -> None:
    b.write(_u8.pack(v))


def _wb(b: BytesIO, v: bytes) -> None:
    _wu32(b, len(v))
    b.write(v)


def _ws(b: BytesIO, v: str) -> None:
    _wb(b, v.encode("utf-8"))


class _R:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise WireError(f"short read: want {n} at {self.pos}")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u64(self) -> int:
        return _u64.unpack(self.take(8))[0]

    def u32(self) -> int:
        return _u32.unpack(self.take(4))[0]

    def u8(self) -> int:
        return _u8.unpack(self.take(1))[0]

    def blob(self) -> bytes:
        n = self.u32()
        if n > MAX_PAYLOAD:
            raise WireError(f"blob too large: {n}")
        return self.take(n)

    def s(self) -> str:
        return self.blob().decode("utf-8")

    def count(self) -> int:
        n = self.u32()
        if n > MAX_ITEMS:
            raise WireError(f"count too large: {n}")
        return n


# ---------------------------------------------------------------------------
# entries / membership / snapshots
# ---------------------------------------------------------------------------
def _w_entry(b: BytesIO, e: Entry) -> None:
    _wu64(b, e.term)
    _wu64(b, e.index)
    _wu8(b, int(e.type))
    _wu64(b, e.key)
    _wu64(b, e.client_id)
    _wu64(b, e.series_id)
    _wu64(b, e.responded_to)
    _wb(b, e.cmd)


def _r_entry(r: _R) -> Entry:
    term = r.u64()
    index = r.u64()
    etype = EntryType(r.u8())
    key = r.u64()
    client_id = r.u64()
    series_id = r.u64()
    responded_to = r.u64()
    cmd = r.blob()
    return Entry(
        term=term,
        index=index,
        type=etype,
        key=key,
        client_id=client_id,
        series_id=series_id,
        responded_to=responded_to,
        cmd=cmd,
    )


def _w_addr_map(b: BytesIO, m: dict) -> None:
    _wu32(b, len(m))
    for rid in sorted(m):
        _wu64(b, rid)
        _ws(b, m[rid])


def _r_addr_map(r: _R) -> dict:
    return {r.u64(): r.s() for _ in range(r.count())}


def _w_membership(b: BytesIO, m: Membership) -> None:
    _wu64(b, m.config_change_id)
    _w_addr_map(b, m.addresses)
    _w_addr_map(b, m.non_votings)
    _w_addr_map(b, m.witnesses)
    _wu32(b, len(m.removed))
    for rid in sorted(m.removed):
        _wu64(b, rid)


def _r_membership(r: _R) -> Membership:
    ccid = r.u64()
    addresses = _r_addr_map(r)
    non_votings = _r_addr_map(r)
    witnesses = _r_addr_map(r)
    removed = {r.u64(): True for _ in range(r.count())}
    return Membership(
        config_change_id=ccid,
        addresses=addresses,
        non_votings=non_votings,
        witnesses=witnesses,
        removed=removed,
    )


def _w_snapshot(b: BytesIO, s: Snapshot) -> None:
    _ws(b, s.filepath)
    _wu64(b, s.file_size)
    _wu64(b, s.index)
    _wu64(b, s.term)
    _w_membership(b, s.membership)
    _wu32(b, len(s.files))
    for f in s.files:
        _wu64(b, f.file_id)
        _ws(b, f.filepath)
        _wu64(b, f.file_size)
        _wb(b, f.metadata)
    _wb(b, s.checksum)
    _wu8(b, int(s.dummy))
    _wu64(b, s.shard_id)
    _wu64(b, s.replica_id)
    _wu64(b, s.on_disk_index)
    _wu8(b, int(s.witness))
    _wu8(b, int(s.imported))
    _wu8(b, s.type)
    _wu8(b, int(s.compression))


def _r_snapshot(r: _R) -> Snapshot:
    filepath = r.s()
    file_size = r.u64()
    index = r.u64()
    term = r.u64()
    membership = _r_membership(r)
    files = tuple(
        SnapshotFile(
            file_id=r.u64(),
            filepath=r.s(),
            file_size=r.u64(),
            metadata=r.blob(),
        )
        for _ in range(r.count())
    )
    checksum = r.blob()
    dummy = bool(r.u8())
    shard_id = r.u64()
    replica_id = r.u64()
    on_disk_index = r.u64()
    witness = bool(r.u8())
    imported = bool(r.u8())
    stype = r.u8()
    compression = CompressionType(r.u8())
    return Snapshot(
        filepath=filepath,
        file_size=file_size,
        index=index,
        term=term,
        membership=membership,
        files=files,
        checksum=checksum,
        dummy=dummy,
        shard_id=shard_id,
        replica_id=replica_id,
        on_disk_index=on_disk_index,
        witness=witness,
        imported=imported,
        type=stype,
        compression=compression,
    )


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------
def _w_message(b: BytesIO, m: Message) -> None:
    _wu8(b, int(m.type))
    _wu8(b, int(m.reject))
    for v in (
        m.to,
        m.from_,
        m.shard_id,
        m.term,
        m.log_term,
        m.log_index,
        m.commit,
        m.hint,
        m.hint_high,
    ):
        _wu64(b, v)
    _wu32(b, len(m.entries))
    for e in m.entries:
        _w_entry(b, e)
    has_ss = not m.snapshot.is_empty()
    _wu8(b, int(has_ss))
    if has_ss:
        _w_snapshot(b, m.snapshot)
    # trace context (obs/): one flag byte when untraced, so the
    # tracing-off wire cost is a single zero byte per message
    has_trace = m.trace_id != 0
    _wu8(b, int(has_trace))
    if has_trace:
        _wu64(b, m.trace_id)
        _wu64(b, m.span_id)


def _r_message(r: _R, bin_ver: int = MESSAGE_BATCH_BIN_VER) -> Message:
    mtype = MessageType(r.u8())
    reject = bool(r.u8())
    to, from_, shard_id, term, log_term, log_index, commit, hint, hint_high = (
        r.u64() for _ in range(9)
    )
    entries = tuple(_r_entry(r) for _ in range(r.count()))
    snapshot = _r_snapshot(r) if r.u8() else Snapshot()
    trace_id = span_id = 0
    # v0 predates the trace-context flag byte: nothing more to read
    if bin_ver >= 1 and r.u8():
        trace_id = r.u64()
        span_id = r.u64()
    return Message(
        type=mtype,
        to=to,
        from_=from_,
        shard_id=shard_id,
        term=term,
        log_term=log_term,
        log_index=log_index,
        commit=commit,
        reject=reject,
        hint=hint,
        hint_high=hint_high,
        entries=entries,
        snapshot=snapshot,
        trace_id=trace_id,
        span_id=span_id,
    )


# ---------------------------------------------------------------------------
# top-level payloads
# ---------------------------------------------------------------------------
def encode_batch(batch: MessageBatch) -> bytes:
    b = BytesIO()
    _ws(b, batch.source_address)
    _wu64(b, batch.deployment_id)
    # the encoder only emits the CURRENT per-message layout, so the
    # header always says so — batch.bin_ver is what the decoder READ,
    # not a request to re-encode an old format
    _wu32(b, MESSAGE_BATCH_BIN_VER)
    _wu32(b, len(batch.messages))
    for m in batch.messages:
        _w_message(b, m)
    return b.getvalue()


def decode_batch(data: bytes) -> MessageBatch:
    r = _R(data)
    source_address = r.s()
    deployment_id = r.u64()
    bin_ver = r.u32()
    if bin_ver > MESSAGE_BATCH_BIN_VER:
        # the per-message layout is versioned by this field; parsing an
        # unknown FUTURE version would silently shift every subsequent
        # field.  Known past versions still decode (v0 lacks the
        # trace-context flag byte) so a rolling upgrade keeps talking.
        raise WireError(
            f"message batch bin_ver {bin_ver} is newer than supported "
            f"{MESSAGE_BATCH_BIN_VER}"
        )
    messages = tuple(_r_message(r, bin_ver) for _ in range(r.count()))
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return MessageBatch(
        messages=messages,
        source_address=source_address,
        deployment_id=deployment_id,
        bin_ver=bin_ver,
    )


def encode_snapshot_meta(s: Snapshot) -> bytes:
    """Standalone Snapshot metadata record (snapshot export dirs)."""
    b = BytesIO()
    _w_snapshot(b, s)
    return b.getvalue()


def decode_snapshot_meta(data: bytes) -> Snapshot:
    r = _R(data)
    s = _r_snapshot(r)
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return s


_CF_WITNESS = 1
_CF_DUMMY = 2
_CF_FILE_INFO = 4


def encode_chunk(c: Chunk) -> bytes:
    b = BytesIO()
    for v in (
        c.shard_id,
        c.replica_id,
        c.from_,
        c.chunk_id,
        c.chunk_size,
        c.chunk_count,
        c.index,
        c.term,
        c.message_term,
        c.file_size,
        c.on_disk_index,
    ):
        _wu64(b, v)
    flags = (
        (_CF_WITNESS if c.witness else 0)
        | (_CF_DUMMY if c.dummy else 0)
        | (_CF_FILE_INFO if c.has_file_info else 0)
    )
    _wu8(b, flags)
    _ws(b, c.filepath)
    _wb(b, c.data)
    _w_membership(b, c.membership)
    if c.has_file_info:
        _wu64(b, c.file_info.file_id)
        _ws(b, c.file_info.filepath)
        _wu64(b, c.file_info.file_size)
        _wb(b, c.file_info.metadata)
        _wu64(b, c.file_chunk_id)
        _wu64(b, c.file_chunk_count)
    return b.getvalue()


def decode_chunk(data: bytes) -> Chunk:
    r = _R(data)
    (
        shard_id,
        replica_id,
        from_,
        chunk_id,
        chunk_size,
        chunk_count,
        index,
        term,
        message_term,
        file_size,
        on_disk_index,
    ) = (r.u64() for _ in range(11))
    flags = r.u8()
    filepath = r.s()
    payload = r.blob()
    membership = _r_membership(r)
    file_info = SnapshotFile()
    file_chunk_id = file_chunk_count = 0
    if flags & _CF_FILE_INFO:
        file_info = SnapshotFile(
            file_id=r.u64(),
            filepath=r.s(),
            file_size=r.u64(),
            metadata=r.blob(),
        )
        file_chunk_id = r.u64()
        file_chunk_count = r.u64()
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return Chunk(
        shard_id=shard_id,
        replica_id=replica_id,
        from_=from_,
        chunk_id=chunk_id,
        chunk_size=chunk_size,
        chunk_count=chunk_count,
        index=index,
        term=term,
        message_term=message_term,
        file_size=file_size,
        on_disk_index=on_disk_index,
        witness=bool(flags & _CF_WITNESS),
        dummy=bool(flags & _CF_DUMMY),
        has_file_info=bool(flags & _CF_FILE_INFO),
        filepath=filepath,
        data=payload,
        membership=membership,
        file_info=file_info,
        file_chunk_id=file_chunk_id,
        file_chunk_count=file_chunk_count,
    )


# ---------------------------------------------------------------------------
# rsm payload codecs
# ---------------------------------------------------------------------------
# These payloads ride INSIDE entries and snapshot chunks, so they arrive
# from the network exactly like frames do: config-change cmds replicate
# to every peer, session tables and rsm snapshot payloads ship through
# the chunk lane.  The reference encodes them as protobufs
# (raftpb/raft.proto -> ConfigChange, session state [U]); here they use
# the same positional binary discipline as the rest of this module —
# never pickle, which would be remote code execution on decode.

def encode_config_change(cc: "ConfigChange") -> bytes:
    b = BytesIO()
    _wu64(b, cc.config_change_id)
    _wu8(b, int(cc.type))
    _wu64(b, cc.replica_id)
    _ws(b, cc.address)
    _wu8(b, int(cc.initialize))
    return b.getvalue()


def decode_config_change(data: bytes) -> "ConfigChange":
    r = _R(data)
    ccid = r.u64()
    cctype = ConfigChangeType(r.u8())
    replica_id = r.u64()
    address = r.s()
    initialize = bool(r.u8())
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return ConfigChange(
        config_change_id=ccid,
        type=cctype,
        replica_id=replica_id,
        address=address,
        initialize=initialize,
    )


def encode_session_table(sessions) -> bytes:
    """``sessions``: iterable of (client_id, responded_to,
    {series_id: Result}) in LRU order (order is preserved)."""
    b = BytesIO()
    rows = list(sessions)
    _wu32(b, len(rows))
    for client_id, responded_to, history in rows:
        _wu64(b, client_id)
        _wu64(b, responded_to)
        _wu32(b, len(history))
        for sid in sorted(history):
            res = history[sid]
            _wu64(b, sid)
            _wu64(b, res.value)
            _wb(b, res.data)
    return b.getvalue()


def decode_session_table(data: bytes):
    from ..statemachine import Result

    r = _R(data)
    out = []
    for _ in range(r.count()):
        client_id = r.u64()
        responded_to = r.u64()
        history = {}
        for _ in range(r.count()):
            sid = r.u64()
            value = r.u64()
            rdata = r.blob()
            history[sid] = Result(value=value, data=rdata)
        out.append((client_id, responded_to, history))
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return out


RSM_SNAPSHOT_VERSION = 2


def encode_rsm_snapshot(
    *,
    index: int,
    term: int,
    membership: Membership,
    sessions: bytes,
    sm_data,
    on_disk: bool,
) -> bytes:
    b = BytesIO()
    _wu8(b, RSM_SNAPSHOT_VERSION)
    _wu8(b, int(on_disk))
    _wu8(b, 0 if sm_data is None else 1)
    _wu64(b, index)
    _wu64(b, term)
    _w_membership(b, membership)
    _wb(b, sessions)
    _wb(b, sm_data if sm_data is not None else b"")
    return b.getvalue()


def decode_rsm_snapshot(data: bytes) -> dict:
    r = _R(data)
    version = r.u8()
    if version != RSM_SNAPSHOT_VERSION:
        raise WireError(f"unsupported rsm snapshot version {version}")
    on_disk = bool(r.u8())
    has_sm_data = bool(r.u8())
    index = r.u64()
    term = r.u64()
    membership = _r_membership(r)
    sessions = r.blob()
    sm_data = r.blob()
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return {
        "version": version,
        "index": index,
        "term": term,
        "membership": membership,
        "sessions": sessions,
        "sm_data": sm_data if has_sm_data else None,
        "on_disk": on_disk,
    }
