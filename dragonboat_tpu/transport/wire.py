"""Binary wire codec for the TCP transport.

reference: the reference serializes raftpb protobufs onto a framed TCP
stream (internal/transport/tcp.go [U]).  This codec is a hand-rolled
positional binary format (length-prefixed, little-endian, crc-framed by
the transport) rather than pickle: wire input is untrusted and must
never be able to execute code or allocate unboundedly on decode.

Frame layout (transport level, see tcp.py):
    magic  u32  = 0x54524654 ("TRFT")
    kind   u8   (1 = MessageBatch, 2 = Chunk; the 0x80 bit flags a
                 zlib-compressed payload — crc/length cover the bytes
                 as sent, i.e. the compressed form)
    length u32  payload byte length
    crc    u32  zlib.crc32 of payload
    payload
"""
from __future__ import annotations

import struct
import zlib
from io import BytesIO
from typing import Tuple

from ..pb import (
    Chunk,
    CompressionType,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    MASK64,
    MESSAGE_BATCH_BIN_VER,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotFile,
)

MAGIC = 0x54524654
KIND_BATCH = 1
KIND_CHUNK = 2
# resumable snapshot streams (docs/BIGSTATE.md): a reconnecting sender
# asks the receiver for its receive cursor before re-streaming.  The
# query payload is an encoded data-less Chunk carrying the stream
# identity; the response is one little-endian u64 (the next chunk
# offset the receiver needs, 0 = restart).  Unknown kinds close the
# connection on OLD receivers, which the sender treats as cursor 0 —
# rolling upgrades degrade to restart-from-zero, never to corruption.
KIND_RESUME_QUERY = 3
KIND_RESUME_RESP = 4
# gateway RPC ingress (gateway/rpc.py, docs/GATEWAY.md "Networked
# ingress"): one request frame out, one response frame back, multiplexed
# by request id over a long-lived client connection.  Same CRC framing
# and the same versioned-payload discipline as KIND_BATCH (RPC_BIN_VER
# below); unknown kinds still close the connection on OLD receivers, so
# a client probing a pre-RPC node degrades to a torn connection its
# breaker absorbs — never to misparsed frames.
KIND_RPC_REQ = 5
KIND_RPC_RESP = 6
# frame-kind flag: payload is zlib-compressed (wire entry compression —
# reference: EntryCompression on replicated batches [U]; ours is adaptive)
KIND_COMPRESSED = 0x80
WIRE_COMPRESS_THRESHOLD = 1024

# decode-side sanity bounds (wire input is untrusted)
MAX_PAYLOAD = 256 * 1024 * 1024
MAX_ITEMS = 1 << 20

# all protocol integers are uint64, like the reference's raftpb (session
# series ids use the top of the range, e.g. SERIES_ID_REGISTER)
_u64 = struct.Struct("<Q")
_u32 = struct.Struct("<I")
_u8 = struct.Struct("<B")


class WireError(Exception):
    """Malformed or out-of-bounds wire data."""


def maybe_compress(
    kind: int,
    payload: bytes,
    flag: int,
    threshold: int,
    max_out: int = MAX_PAYLOAD,
):
    """Adaptive compression shared by the TCP framing and the tan WAL:
    payloads over ``threshold`` that actually shrink get ``flag`` OR'd
    into the kind byte (reference: EntryCompression [U]).

    Never compresses past ``max_out``, the decode side's
    bounded_decompress limit — a compressed payload that inflates beyond
    it would encode fine and then fail on every decode."""
    if threshold <= len(payload) <= max_out:
        z = zlib.compress(payload, 1)  # speed level: hot paths
        if len(z) < len(payload):
            return kind | flag, z
    return kind, payload


def bounded_decompress(payload: bytes, max_out: int) -> bytes:
    """Strict inverse of maybe_compress's compressed arm: bounded
    allocation (zlib-bomb safe) and no trailing bytes tolerated."""
    try:
        d = zlib.decompressobj()
        out = d.decompress(payload, max_out + 1)
    except zlib.error as e:
        raise WireError(f"bad compressed payload: {e}")
    if len(out) > max_out or not d.eof:
        raise WireError("decompressed payload too large")
    if d.unused_data:
        raise WireError("trailing bytes after compressed payload")
    return out


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------
def _wu64(b: BytesIO, v: int) -> None:
    # mask, don't raise: uint64 wraparound parity (pb.MASK64 policy)
    b.write(_u64.pack(v & MASK64))


def _wu32(b: BytesIO, v: int) -> None:
    b.write(_u32.pack(v))


def _wu8(b: BytesIO, v: int) -> None:
    b.write(_u8.pack(v))


def _wb(b: BytesIO, v: bytes) -> None:
    _wu32(b, len(v))
    b.write(v)


def _ws(b: BytesIO, v: str) -> None:
    _wb(b, v.encode("utf-8"))


class _R:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise WireError(f"short read: want {n} at {self.pos}")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u64(self) -> int:
        return _u64.unpack(self.take(8))[0]

    def u32(self) -> int:
        return _u32.unpack(self.take(4))[0]

    def u8(self) -> int:
        return _u8.unpack(self.take(1))[0]

    def blob(self) -> bytes:
        n = self.u32()
        if n > MAX_PAYLOAD:
            raise WireError(f"blob too large: {n}")
        return self.take(n)

    def s(self) -> str:
        try:
            return self.blob().decode("utf-8")
        except UnicodeDecodeError as e:
            # UnicodeDecodeError is a ValueError; the frame-error
            # contract (wirecheck fuzz) wants the narrow type so the
            # transport loop never has to catch anything broader
            raise WireError(f"invalid utf-8 string field: {e}")

    def count(self) -> int:
        n = self.u32()
        if n > MAX_ITEMS:
            raise WireError(f"count too large: {n}")
        return n


def _enum(cls, v: int):
    """Enum conversion under the frame-error contract: an unknown
    discriminant byte is malformed wire data (WireError), not a
    ValueError leaking enum internals to the transport loop."""
    try:
        return cls(v)
    except ValueError:
        raise WireError(f"unknown {cls.__name__} value {v}")


# ---------------------------------------------------------------------------
# entries / membership / snapshots
# ---------------------------------------------------------------------------
def _w_entry(b: BytesIO, e: Entry) -> None:
    _wu64(b, e.term)
    _wu64(b, e.index)
    _wu8(b, int(e.type))
    _wu64(b, e.key)
    _wu64(b, e.client_id)
    _wu64(b, e.series_id)
    _wu64(b, e.responded_to)
    _wb(b, e.cmd)


def _r_entry(r: _R) -> Entry:
    term = r.u64()
    index = r.u64()
    etype = _enum(EntryType, r.u8())
    key = r.u64()
    client_id = r.u64()
    series_id = r.u64()
    responded_to = r.u64()
    cmd = r.blob()
    return Entry(
        term=term,
        index=index,
        type=etype,
        key=key,
        client_id=client_id,
        series_id=series_id,
        responded_to=responded_to,
        cmd=cmd,
    )


def _w_addr_map(b: BytesIO, m: dict) -> None:
    _wu32(b, len(m))
    for rid in sorted(m):
        _wu64(b, rid)
        _ws(b, m[rid])


def _r_addr_map(r: _R) -> dict:
    return {r.u64(): r.s() for _ in range(r.count())}


def _w_membership(b: BytesIO, m: Membership) -> None:
    _wu64(b, m.config_change_id)
    _w_addr_map(b, m.addresses)
    _w_addr_map(b, m.non_votings)
    _w_addr_map(b, m.witnesses)
    _wu32(b, len(m.removed))
    for rid in sorted(m.removed):
        _wu64(b, rid)


def _r_membership(r: _R) -> Membership:
    ccid = r.u64()
    addresses = _r_addr_map(r)
    non_votings = _r_addr_map(r)
    witnesses = _r_addr_map(r)
    removed = {r.u64(): True for _ in range(r.count())}
    return Membership(
        config_change_id=ccid,
        addresses=addresses,
        non_votings=non_votings,
        witnesses=witnesses,
        removed=removed,
    )


def _w_snapshot(b: BytesIO, s: Snapshot) -> None:
    _ws(b, s.filepath)
    _wu64(b, s.file_size)
    _wu64(b, s.index)
    _wu64(b, s.term)
    _w_membership(b, s.membership)
    _wu32(b, len(s.files))
    for f in s.files:
        _wu64(b, f.file_id)
        _ws(b, f.filepath)
        _wu64(b, f.file_size)
        _wb(b, f.metadata)
    _wb(b, s.checksum)
    _wu8(b, int(s.dummy))
    _wu64(b, s.shard_id)
    _wu64(b, s.replica_id)
    _wu64(b, s.on_disk_index)
    _wu8(b, int(s.witness))
    _wu8(b, int(s.imported))
    _wu8(b, s.type)
    _wu8(b, int(s.compression))


def _r_snapshot(r: _R) -> Snapshot:
    filepath = r.s()
    file_size = r.u64()
    index = r.u64()
    term = r.u64()
    membership = _r_membership(r)
    files = tuple(
        SnapshotFile(
            file_id=r.u64(),
            filepath=r.s(),
            file_size=r.u64(),
            metadata=r.blob(),
        )
        for _ in range(r.count())
    )
    checksum = r.blob()
    dummy = bool(r.u8())
    shard_id = r.u64()
    replica_id = r.u64()
    on_disk_index = r.u64()
    witness = bool(r.u8())
    imported = bool(r.u8())
    stype = r.u8()
    compression = _enum(CompressionType, r.u8())
    return Snapshot(
        filepath=filepath,
        file_size=file_size,
        index=index,
        term=term,
        membership=membership,
        files=files,
        checksum=checksum,
        dummy=dummy,
        shard_id=shard_id,
        replica_id=replica_id,
        on_disk_index=on_disk_index,
        witness=witness,
        imported=imported,
        type=stype,
        compression=compression,
    )


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------
def _w_message(b: BytesIO, m: Message) -> None:
    _wu8(b, int(m.type))
    _wu8(b, int(m.reject))
    for v in (
        m.to,
        m.from_,
        m.shard_id,
        m.term,
        m.log_term,
        m.log_index,
        m.commit,
        m.hint,
        m.hint_high,
    ):
        _wu64(b, v)
    _wu32(b, len(m.entries))
    for e in m.entries:
        _w_entry(b, e)
    has_ss = not m.snapshot.is_empty()
    _wu8(b, int(has_ss))
    if has_ss:
        _w_snapshot(b, m.snapshot)
    # trace context (obs/): one flag byte when untraced, so the
    # tracing-off wire cost is a single zero byte per message
    has_trace = m.trace_id != 0
    _wu8(b, int(has_trace))
    if has_trace:
        _wu64(b, m.trace_id)
        _wu64(b, m.span_id)


def _r_message(r: _R, bin_ver: int = MESSAGE_BATCH_BIN_VER) -> Message:
    mtype = _enum(MessageType, r.u8())
    reject = bool(r.u8())
    to, from_, shard_id, term, log_term, log_index, commit, hint, hint_high = (
        r.u64() for _ in range(9)
    )
    entries = tuple(_r_entry(r) for _ in range(r.count()))
    snapshot = _r_snapshot(r) if r.u8() else Snapshot()
    trace_id = span_id = 0
    # v0 predates the trace-context flag byte: nothing more to read
    if bin_ver >= 1 and r.u8():
        trace_id = r.u64()
        span_id = r.u64()
    return Message(
        type=mtype,
        to=to,
        from_=from_,
        shard_id=shard_id,
        term=term,
        log_term=log_term,
        log_index=log_index,
        commit=commit,
        reject=reject,
        hint=hint,
        hint_high=hint_high,
        entries=entries,
        snapshot=snapshot,
        trace_id=trace_id,
        span_id=span_id,
    )


# ---------------------------------------------------------------------------
# top-level payloads
# ---------------------------------------------------------------------------
def encode_batch(batch: MessageBatch) -> bytes:
    b = BytesIO()
    _ws(b, batch.source_address)
    _wu64(b, batch.deployment_id)
    # the encoder only emits the CURRENT per-message layout, so the
    # header always says so — batch.bin_ver is what the decoder READ,
    # not a request to re-encode an old format
    _wu32(b, MESSAGE_BATCH_BIN_VER)
    _wu32(b, len(batch.messages))
    for m in batch.messages:
        _w_message(b, m)
    return b.getvalue()


def decode_batch(data: bytes) -> MessageBatch:
    r = _R(data)
    source_address = r.s()
    deployment_id = r.u64()
    bin_ver = r.u32()
    if bin_ver > MESSAGE_BATCH_BIN_VER:
        # the per-message layout is versioned by this field; parsing an
        # unknown FUTURE version would silently shift every subsequent
        # field.  Known past versions still decode (v0 lacks the
        # trace-context flag byte) so a rolling upgrade keeps talking.
        raise WireError(
            f"message batch bin_ver {bin_ver} is newer than supported "
            f"{MESSAGE_BATCH_BIN_VER}"
        )
    messages = tuple(_r_message(r, bin_ver) for _ in range(r.count()))
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return MessageBatch(
        messages=messages,
        source_address=source_address,
        deployment_id=deployment_id,
        bin_ver=bin_ver,
    )


def encode_snapshot_meta(s: Snapshot) -> bytes:
    """Standalone Snapshot metadata record (snapshot export dirs)."""
    b = BytesIO()
    _w_snapshot(b, s)
    return b.getvalue()


def decode_snapshot_meta(data: bytes) -> Snapshot:
    r = _R(data)
    s = _r_snapshot(r)
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return s


_CF_WITNESS = 1
_CF_DUMMY = 2
_CF_FILE_INFO = 4


# per-chunk payload bound, enforced BOTH ways (the OBS-reply
# discipline): legit chunks are Soft.snapshot_chunk_size (2MB default),
# so a length field anywhere near this is a forged frame, not data
_CHUNK_MAX_DATA = 16 * 1024 * 1024


def encode_chunk(c: Chunk) -> bytes:
    if len(c.data) > _CHUNK_MAX_DATA:
        raise WireError(
            f"chunk data {len(c.data)}B exceeds {_CHUNK_MAX_DATA}B"
        )
    b = BytesIO()
    for v in (
        c.shard_id,
        c.replica_id,
        c.from_,
        c.chunk_id,
        c.chunk_size,
        c.chunk_count,
        c.index,
        c.term,
        c.message_term,
        c.file_size,
        c.on_disk_index,
    ):
        _wu64(b, v)
    flags = (
        (_CF_WITNESS if c.witness else 0)
        | (_CF_DUMMY if c.dummy else 0)
        | (_CF_FILE_INFO if c.has_file_info else 0)
    )
    _wu8(b, flags)
    _ws(b, c.filepath)
    _wb(b, c.data)
    _w_membership(b, c.membership)
    if c.has_file_info:
        _wu64(b, c.file_info.file_id)
        _ws(b, c.file_info.filepath)
        _wu64(b, c.file_info.file_size)
        _wb(b, c.file_info.metadata)
        _wu64(b, c.file_chunk_id)
        _wu64(b, c.file_chunk_count)
    return b.getvalue()


def decode_chunk(data: bytes) -> Chunk:
    r = _R(data)
    (
        shard_id,
        replica_id,
        from_,
        chunk_id,
        chunk_size,
        chunk_count,
        index,
        term,
        message_term,
        file_size,
        on_disk_index,
    ) = (r.u64() for _ in range(11))
    flags = r.u8()
    filepath = r.s()
    payload = r.blob()
    if len(payload) > _CHUNK_MAX_DATA:
        raise WireError(
            f"chunk data {len(payload)}B exceeds {_CHUNK_MAX_DATA}B"
        )
    membership = _r_membership(r)
    file_info = SnapshotFile()
    file_chunk_id = file_chunk_count = 0
    if flags & _CF_FILE_INFO:
        file_info = SnapshotFile(
            file_id=r.u64(),
            filepath=r.s(),
            file_size=r.u64(),
            metadata=r.blob(),
        )
        file_chunk_id = r.u64()
        file_chunk_count = r.u64()
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return Chunk(
        shard_id=shard_id,
        replica_id=replica_id,
        from_=from_,
        chunk_id=chunk_id,
        chunk_size=chunk_size,
        chunk_count=chunk_count,
        index=index,
        term=term,
        message_term=message_term,
        file_size=file_size,
        on_disk_index=on_disk_index,
        witness=bool(flags & _CF_WITNESS),
        dummy=bool(flags & _CF_DUMMY),
        has_file_info=bool(flags & _CF_FILE_INFO),
        filepath=filepath,
        data=payload,
        membership=membership,
        file_info=file_info,
        file_chunk_id=file_chunk_id,
        file_chunk_count=file_chunk_count,
    )


# ---------------------------------------------------------------------------
# rsm payload codecs
# ---------------------------------------------------------------------------
# These payloads ride INSIDE entries and snapshot chunks, so they arrive
# from the network exactly like frames do: config-change cmds replicate
# to every peer, session tables and rsm snapshot payloads ship through
# the chunk lane.  The reference encodes them as protobufs
# (raftpb/raft.proto -> ConfigChange, session state [U]); here they use
# the same positional binary discipline as the rest of this module —
# never pickle, which would be remote code execution on decode.

def encode_config_change(cc: "ConfigChange") -> bytes:
    b = BytesIO()
    _wu64(b, cc.config_change_id)
    _wu8(b, int(cc.type))
    _wu64(b, cc.replica_id)
    _ws(b, cc.address)
    _wu8(b, int(cc.initialize))
    return b.getvalue()


def decode_config_change(data: bytes) -> "ConfigChange":
    r = _R(data)
    ccid = r.u64()
    cctype = _enum(ConfigChangeType, r.u8())
    replica_id = r.u64()
    address = r.s()
    initialize = bool(r.u8())
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return ConfigChange(
        config_change_id=ccid,
        type=cctype,
        replica_id=replica_id,
        address=address,
        initialize=initialize,
    )


# per-result payload bound, both ways: cached session results are
# proposal-sized, never snapshot-sized
_SESSION_MAX_RESULT = 8 * 1024 * 1024


def encode_session_table(sessions) -> bytes:
    """``sessions``: iterable of (client_id, responded_to,
    {series_id: Result}) in LRU order (order is preserved)."""
    b = BytesIO()
    rows = list(sessions)
    _wu32(b, len(rows))
    for client_id, responded_to, history in rows:
        _wu64(b, client_id)
        _wu64(b, responded_to)
        _wu32(b, len(history))
        for sid in sorted(history):
            res = history[sid]
            if len(res.data) > _SESSION_MAX_RESULT:
                raise WireError(
                    f"session result {len(res.data)}B exceeds "
                    f"{_SESSION_MAX_RESULT}B"
                )
            _wu64(b, sid)
            _wu64(b, res.value)
            _wb(b, res.data)
    return b.getvalue()


def decode_session_table(data: bytes):
    from ..statemachine import Result

    r = _R(data)
    out = []
    for _ in range(r.count()):
        client_id = r.u64()
        responded_to = r.u64()
        history = {}
        for _ in range(r.count()):
            sid = r.u64()
            value = r.u64()
            rdata = r.blob()
            if len(rdata) > _SESSION_MAX_RESULT:
                raise WireError(
                    f"session result {len(rdata)}B exceeds "
                    f"{_SESSION_MAX_RESULT}B"
                )
            history[sid] = Result(value=value, data=rdata)
        out.append((client_id, responded_to, history))
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return out


RSM_SNAPSHOT_VERSION = 2

# session-table section bound, both ways.  sm_data stays at the global
# MAX_PAYLOAD (a full state-machine image is legitimately huge); the
# session table is LRU-capped and can never approach this honestly.
_RSM_MAX_SESSIONS = 64 * 1024 * 1024


def encode_rsm_snapshot(
    *,
    index: int,
    term: int,
    membership: Membership,
    sessions: bytes,
    sm_data,
    on_disk: bool,
) -> bytes:
    if len(sessions) > _RSM_MAX_SESSIONS:
        raise WireError(
            f"session table {len(sessions)}B exceeds {_RSM_MAX_SESSIONS}B"
        )
    b = BytesIO()
    _wu8(b, RSM_SNAPSHOT_VERSION)
    _wu8(b, int(on_disk))
    _wu8(b, 0 if sm_data is None else 1)
    _wu64(b, index)
    _wu64(b, term)
    _w_membership(b, membership)
    _wb(b, sessions)
    _wb(b, sm_data if sm_data is not None else b"")
    return b.getvalue()


def decode_rsm_snapshot(data: bytes) -> dict:
    r = _R(data)
    version = r.u8()
    if version != RSM_SNAPSHOT_VERSION:
        raise WireError(f"unsupported rsm snapshot version {version}")
    on_disk = bool(r.u8())
    has_sm_data = bool(r.u8())
    index = r.u64()
    term = r.u64()
    membership = _r_membership(r)
    sessions = r.blob()
    if len(sessions) > _RSM_MAX_SESSIONS:
        raise WireError(
            f"session table {len(sessions)}B exceeds {_RSM_MAX_SESSIONS}B"
        )
    sm_data = r.blob()
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return {
        "version": version,
        "index": index,
        "term": term,
        "membership": membership,
        "sessions": sessions,
        "sm_data": sm_data if has_sm_data else None,
        "on_disk": on_disk,
    }


# ---------------------------------------------------------------------------
# gateway RPC payloads (gateway/rpc.py)
# ---------------------------------------------------------------------------
# The networked NodeHost front door's request/response pair.  Both are
# versioned like MessageBatch: the encoder always writes the CURRENT
# layout, the decoder accepts known past versions and refuses FUTURE
# ones (silently shifting every later field is the failure mode this
# guards).  All fields positional binary — RPC input arrives from
# untrusted client connections and must never execute code or allocate
# unboundedly on decode.

# v0: the original layout.  v1 appends a trace-context section (flag
# byte + trace_id/span_id, the pb.Message discipline) — but the encoder
# only stamps v1 when trace context is actually present, so an untraced
# request stays BYTE-IDENTICAL to v0 and an old (v0-only) server keeps
# working as long as nobody traces at it.  A traced frame against an
# old server tears the connection (future-version refusal); the client
# handle latches tracing off for that address and retries untraced
# (gateway/rpc.py, docs/OBSERVABILITY.md "Degrade matrix").
RPC_BIN_VER = 1

# request ops
RPC_OP_PROPOSE = 1
RPC_OP_READ = 2
RPC_OP_SESSION_OPEN = 3
RPC_OP_SESSION_CLOSE = 4
RPC_OP_STATS = 5
RPC_OP_FAULT = 6
RPC_OP_OBS = 7  # fleet-scope telemetry (obs/fleetscope.py); old
                # servers answer RPC_ERR "unknown op 7" and the
                # collector marks the process "no-obs"

# READ flags (RpcRequest.flags)
RPC_READ_LEASE = 0   # lease fast path ONLY; ERR_NO_LEASE when not held
RPC_READ_INDEX = 1   # full ReadIndex quorum read
RPC_READ_STALE = 2   # local stale read (no linearizability)
# readplane consistency byte (docs/READPLANE.md).  Old servers answer
# unknown flags with code=RPC_ERR "unknown read mode N" — the client's
# readplane router treats that as ReadUnsupported and degrades to a
# leader read, so mixed-version fleets stay correct.
RPC_READ_FOLLOWER = 3  # follower-linearizable: ReadIndex round via the
                       # leader, served from the LOCAL state machine
RPC_READ_BOUNDED = 4   # bounded staleness: local read stamped with the
                       # applied index; arg = bound in ticks, shed past it

# STATS request flag: append the read-path serve counts as a trailing
# payload section.  Flag-gated because OLD decoders reject trailing
# bytes — a new server must never send the section unsolicited.
RPC_STATS_READ_PATHS = 1

# OBS sub-kinds (RpcRequest.flags for RPC_OP_OBS)
RPC_OBS_METRICS = 1   # structured MetricsRegistry.snapshot() + identity
RPC_OBS_RECORDER = 2  # flight-recorder ring slice past a cursor
RPC_OBS_SPANS = 3     # finished-span ring slice past a cursor

# response codes: 0..6 are RequestResultCode values verbatim; the 0x60
# block is transport/ingress-level outcomes that have no node-side code
RPC_ERR_BUSY = 0x60       # shed (server admission / node SystemBusy)
RPC_ERR_NOT_FOUND = 0x61  # shard not on this host / host closed
RPC_ERR_NO_LEASE = 0x62   # lease-only read: lease not held, fall back
RPC_ERR = 0x63            # anything else (error string carries detail)
RPC_ERR_DENIED = 0x64     # op not allowed (fault ops on a prod server)
RPC_ERR_STALE_BOUND = 0x65  # BOUNDED read shed: staleness past the bound

_RPC_MAX_CMD = 8 * 1024 * 1024  # per-request payload bound (ingress)


class RpcRequest:
    """One client request (see gateway/rpc.py for op semantics).

    ``client_id``/``series_id``/``responded_to`` carry the exactly-once
    session triple for PROPOSE/SESSION_CLOSE (the session STATE lives
    client-side; the server reconstructs an ephemeral Session per
    request).  ``timeout_ms`` is the per-request deadline the server
    bounds its own wait by; ``arg`` is op-specific (lease margin ticks
    for READ/LEASE).  ``trace_id``/``span_id`` carry the client root
    span's context (0 = untraced) so a gateway propose stitches into
    the server-side request→raft→apply spans — same contract as
    ``pb.Message.trace_id``."""

    __slots__ = ("req_id", "op", "flags", "shard_id", "client_id",
                 "series_id", "responded_to", "timeout_ms", "arg",
                 "payload", "trace_id", "span_id")

    def __init__(self, req_id=0, op=0, flags=0, shard_id=0, client_id=0,
                 series_id=0, responded_to=0, timeout_ms=1000, arg=0,
                 payload=b"", trace_id=0, span_id=0):
        self.req_id = req_id
        self.op = op
        self.flags = flags
        self.shard_id = shard_id
        self.client_id = client_id
        self.series_id = series_id
        self.responded_to = responded_to
        self.timeout_ms = timeout_ms
        self.arg = arg
        self.payload = payload
        self.trace_id = trace_id
        self.span_id = span_id


class RpcResponse:
    """One server response.  ``code`` is a RequestResultCode value or an
    RPC_ERR_* constant; ``value``/``data`` mirror statemachine.Result;
    ``error`` is human-readable detail for the error block."""

    __slots__ = ("req_id", "code", "value", "data", "error")

    def __init__(self, req_id=0, code=0, value=0, data=b"", error=""):
        self.req_id = req_id
        self.code = code
        self.value = value
        self.data = data
        self.error = error


def encode_rpc_request(q: RpcRequest) -> bytes:
    if len(q.payload) > _RPC_MAX_CMD:
        raise WireError(f"rpc payload too large: {len(q.payload)}")
    # v1 is stamped ONLY when trace context rides the frame: untraced
    # requests stay byte-identical to v0, so mixed-version fleets only
    # pay the degrade path when someone actually traces at an old
    # server (and the client latch then falls back to v0 frames)
    traced = bool(q.trace_id)
    b = BytesIO()
    _wu32(b, RPC_BIN_VER if traced else 0)
    _wu64(b, q.req_id)
    _wu8(b, q.op)
    _wu8(b, q.flags)
    _wu64(b, q.shard_id)
    _wu64(b, q.client_id)
    _wu64(b, q.series_id)
    _wu64(b, q.responded_to)
    _wu32(b, q.timeout_ms)
    _wu32(b, q.arg)
    _wb(b, q.payload)
    if traced:
        _wu8(b, 1)
        _wu64(b, q.trace_id)
        _wu64(b, q.span_id)
    return b.getvalue()


def decode_rpc_request(data: bytes) -> RpcRequest:
    r = _R(data)
    bin_ver = r.u32()
    if bin_ver > RPC_BIN_VER:
        raise WireError(
            f"rpc request bin_ver {bin_ver} is newer than supported "
            f"{RPC_BIN_VER}"
        )
    q = RpcRequest(
        req_id=r.u64(), op=r.u8(), flags=r.u8(), shard_id=r.u64(),
        client_id=r.u64(), series_id=r.u64(), responded_to=r.u64(),
        timeout_ms=r.u32(), arg=r.u32(), payload=r.blob(),
    )
    if bin_ver >= 1:
        # trace-context section: flag byte + ids (pb.Message discipline)
        if r.u8():
            q.trace_id = r.u64()
            q.span_id = r.u64()
    if len(q.payload) > _RPC_MAX_CMD:
        raise WireError(f"rpc payload too large: {len(q.payload)}")
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return q


def encode_rpc_response(p: RpcResponse) -> bytes:
    b = BytesIO()
    _wu32(b, RPC_BIN_VER)
    _wu64(b, p.req_id)
    _wu8(b, p.code)
    _wu64(b, p.value)
    _wb(b, p.data)
    _ws(b, p.error)
    return b.getvalue()


def decode_rpc_response(data: bytes) -> RpcResponse:
    r = _R(data)
    bin_ver = r.u32()
    if bin_ver > RPC_BIN_VER:
        raise WireError(
            f"rpc response bin_ver {bin_ver} is newer than supported "
            f"{RPC_BIN_VER}"
        )
    p = RpcResponse(
        req_id=r.u64(), code=r.u8(), value=r.u64(), data=r.blob(),
        error=r.s(),
    )
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return p


# read queries and read results are small tagged values, not arbitrary
# objects: the state machines' lookup() contracts in this repo take
# str/bytes keys and return str/bytes/int/None (plus JSON-able
# composites like AuditKV's ("get", k) tuples and list values).  A
# tagged union keeps the wire pickle-free and the type round trip exact
# (a bytes key must not come back str).
RPC_VAL_NONE = 0
RPC_VAL_BYTES = 1
RPC_VAL_STR = 2
RPC_VAL_INT = 3
RPC_VAL_JSON = 4


def encode_rpc_value(v) -> bytes:
    import json as _json

    b = BytesIO()
    if v is None:
        _wu8(b, RPC_VAL_NONE)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        _wu8(b, RPC_VAL_BYTES)
        _wb(b, bytes(v))
    elif isinstance(v, str):
        _wu8(b, RPC_VAL_STR)
        _ws(b, v)
    elif isinstance(v, bool):
        # bool is an int subclass; JSON keeps the type distinct
        _wu8(b, RPC_VAL_JSON)
        _ws(b, _json.dumps(v))
    elif isinstance(v, int) and 0 <= v <= 0xFFFFFFFFFFFFFFFF:
        _wu8(b, RPC_VAL_INT)
        _wu64(b, v)
    elif isinstance(v, int):
        # negative / oversized ints ride the JSON lane (u64 would wrap)
        _wu8(b, RPC_VAL_JSON)
        _ws(b, _json.dumps(v))
    else:
        try:
            s = _json.dumps(v)
        except (TypeError, ValueError) as e:
            raise WireError(f"rpc value not encodable: {type(v).__name__}") from e
        _wu8(b, RPC_VAL_JSON)
        _ws(b, s)
    return b.getvalue()


def decode_rpc_value(data: bytes):
    import json as _json

    r = _R(data)
    tag = r.u8()
    if tag == RPC_VAL_NONE:
        v = None
    elif tag == RPC_VAL_BYTES:
        v = r.blob()
    elif tag == RPC_VAL_STR:
        v = r.s()
    elif tag == RPC_VAL_INT:
        v = r.u64()
    elif tag == RPC_VAL_JSON:
        try:
            v = _json.loads(r.s())
        except ValueError as e:
            raise WireError(f"bad rpc json value: {e}")
        # JSON turns tuples into lists; lookup() contracts in this repo
        # accept both, so no re-tupling is attempted here
    else:
        raise WireError(f"unknown rpc value tag {tag}")
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return v


# stats bounds, both ways: a host serves thousands of shards at most,
# and the read-path label set is a small fixed vocabulary
_STATS_MAX_ROWS = 1 << 16
_STATS_MAX_READ_PATHS = 1 << 12


def encode_rpc_stats(nodehost_id: str, raft_address: str, rows,
                     read_paths=None) -> bytes:
    """STATS response payload: the host identity plus its
    ``balance_shard_stats()`` rows (membership included), so the
    balance Collector — and through it the gossip-routed gateway's
    RoutingCache — works over RemoteHostHandles with zero shared
    memory.

    ``read_paths`` (path label -> serve count, NodeHost.
    read_path_counts) is a TRAILING section appended only when the
    CLIENT asked for it (RPC_STATS_READ_PATHS in the request flags):
    old decoders reject trailing bytes, so the server must never send
    it unsolicited — flag-gating keeps both skew directions green."""
    b = BytesIO()
    _ws(b, nodehost_id)
    _ws(b, raft_address)
    rows = list(rows)
    if len(rows) > _STATS_MAX_ROWS:
        raise WireError(
            f"stats rows {len(rows)} exceeds {_STATS_MAX_ROWS}"
        )
    if read_paths is not None and len(read_paths) > _STATS_MAX_READ_PATHS:
        raise WireError(
            f"read-path rows {len(read_paths)} exceeds "
            f"{_STATS_MAX_READ_PATHS}"
        )
    _wu32(b, len(rows))
    for row in rows:
        for k in ("shard_id", "replica_id", "leader_id", "term",
                  "applied", "proposals"):
            _wu64(b, row[k])
        # device is -1 (host path / no mesh) or a chip ordinal; +1 keeps
        # it in u64 without a sign convention on the wire
        _wu64(b, int(row.get("device", -1)) + 1)
        _w_membership(b, row["membership"])
    if read_paths is not None:
        _wu32(b, len(read_paths))
        for k in sorted(read_paths):
            _ws(b, k)
            _wu64(b, read_paths[k])
    return b.getvalue()


def decode_rpc_stats(data: bytes):
    r = _R(data)
    nodehost_id = r.s()
    raft_address = r.s()
    rows = []
    n_rows = r.count()
    if n_rows > _STATS_MAX_ROWS:
        raise WireError(f"stats rows {n_rows} exceeds {_STATS_MAX_ROWS}")
    for _ in range(n_rows):
        shard_id = r.u64()
        replica_id = r.u64()
        leader_id = r.u64()
        term = r.u64()
        applied = r.u64()
        proposals = r.u64()
        device = r.u64() - 1
        membership = _r_membership(r)
        rows.append({
            "shard_id": shard_id,
            "replica_id": replica_id,
            "leader_id": leader_id,
            "term": term,
            "applied": applied,
            "proposals": proposals,
            "device": device,
            "membership": membership,
        })
    # optional read-path section (present iff the request asked for it
    # AND the server knows how to send it — an old server just ends
    # here and the caller sees empty counts)
    read_paths = {}
    if r.pos != len(data):
        n_paths = r.count()
        if n_paths > _STATS_MAX_READ_PATHS:
            raise WireError(
                f"read-path rows {n_paths} exceeds {_STATS_MAX_READ_PATHS}"
            )
        for _ in range(n_paths):
            k = r.s()
            read_paths[k] = r.u64()
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return nodehost_id, raft_address, rows, read_paths


# ---------------------------------------------------------------------------
# fleet-scope obs payloads (obs/fleetscope.py, RPC_OP_OBS)
# ---------------------------------------------------------------------------
# The query is positional binary (cursor/epoch don't fit RpcRequest.arg:
# sequence numbers and epochs are u64).  The reply is versioned JSON —
# same lane as RPC_OP_FAULT's spec payload: the content is a nested
# metrics/events/spans dump whose shape evolves faster than a positional
# layout should, and it only ever flows server -> trusted collector.
# Replies are still BOUNDED: every ring is sliced with an explicit
# limit server-side (raftlint's obs-bound rule) and the decoder refuses
# oversized blobs outright.

OBS_BIN_VER = 1

_OBS_MAX_REPLY = 4 * 1024 * 1024  # decoded-reply bound (collector side)


def encode_obs_query(cursor: int = 0, epoch: int = 0,
                     limit: int = 256) -> bytes:
    b = BytesIO()
    _wu32(b, OBS_BIN_VER)
    _wu64(b, cursor)
    _wu64(b, epoch)
    _wu32(b, limit)
    return b.getvalue()


def decode_obs_query(data: bytes):
    """(cursor, epoch, limit); an empty payload decodes as defaults so
    a hand-rolled probe without a query section still answers."""
    if not data:
        return 0, 0, 256
    r = _R(data)
    bin_ver = r.u32()
    if bin_ver > OBS_BIN_VER:
        raise WireError(
            f"obs query bin_ver {bin_ver} is newer than supported "
            f"{OBS_BIN_VER}"
        )
    cursor = r.u64()
    epoch = r.u64()
    limit = r.u32()
    if r.pos != len(data):
        raise WireError(f"trailing bytes: {len(data) - r.pos}")
    return cursor, epoch, limit


def encode_obs_reply(obj: dict) -> bytes:
    import json as _json

    body = {"v": OBS_BIN_VER}
    body.update(obj)
    data = _json.dumps(body, separators=(",", ":")).encode("utf-8")
    if len(data) > _OBS_MAX_REPLY:
        raise WireError(f"obs reply too large: {len(data)}")
    return data


def decode_obs_reply(data: bytes) -> dict:
    import json as _json

    if len(data) > _OBS_MAX_REPLY:
        raise WireError(f"obs reply too large: {len(data)}")
    try:
        obj = _json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"bad obs reply: {e}")
    v = obj.get("v") if isinstance(obj, dict) else None
    if not isinstance(v, int) or v > OBS_BIN_VER or v < 1:
        raise WireError(f"obs reply version {v!r} not supported")
    return obj
