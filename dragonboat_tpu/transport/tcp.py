"""Default cross-host transport: framed TCP.

reference: internal/transport/tcp.go [U] — framed protocol with magic +
kind + size + crc checks, separate lanes for message batches and
snapshot chunks, optional mutual TLS.

Each `get_connection` opens a dedicated socket (the Transport wrapper
above this keeps one connection per target per lane and owns queues,
batching and circuit breaking, exactly like the reference splits
transport.go from tcp.go).  Inbound: one accept loop, one reader thread
per peer socket; a malformed frame (bad magic / crc / overlong payload)
closes the connection — the peer's breaker and resend logic recover.
"""
from __future__ import annotations

import socket
import ssl
import struct
import threading
import zlib
from typing import Optional

from ..logger import get_logger
from ..pb import MASK64, Chunk, MessageBatch
from ..raftio import (
    ChunkHandler,
    IConnection,
    ISnapshotConnection,
    ITransport,
    MessageHandler,
)
from . import wire as wire_mod
from .wire import (
    KIND_BATCH,
    KIND_CHUNK,
    KIND_COMPRESSED,
    KIND_RESUME_QUERY,
    KIND_RESUME_RESP,
    MAGIC,
    MAX_PAYLOAD,
    WIRE_COMPRESS_THRESHOLD,
    WireError,
    decode_batch,
    decode_chunk,
    encode_batch,
    encode_chunk,
)

_log = get_logger("transport")

_header = struct.Struct("<IBII")  # magic, kind, length, crc


def parse_address(addr: str) -> tuple:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _write_frame(sock, kind: int, payload: bytes) -> None:
    if len(payload) > MAX_PAYLOAD:
        # fail locally: the receiver would reject the frame (raw) or the
        # decompressed payload (compressed) and tear the connection down,
        # and the raft layer would retry the same batch forever
        raise WireError(f"payload too large to send: {len(payload)}")
    kind, payload = wire_mod.maybe_compress(
        kind, payload, KIND_COMPRESSED, WIRE_COMPRESS_THRESHOLD
    )
    hdr = _header.pack(MAGIC, kind, len(payload), zlib.crc32(payload))
    sock.sendall(hdr + payload)


def _read_exactly(sock, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf.extend(part)
    return bytes(buf)


def _read_frame(sock) -> Optional[tuple]:
    hdr = _read_exactly(sock, _header.size)
    if hdr is None:
        return None
    magic, kind, length, crc = _header.unpack(hdr)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic:#x}")
    if length > MAX_PAYLOAD:
        raise WireError(f"frame too large: {length}")
    payload = _read_exactly(sock, length)
    if payload is None:
        return None
    if zlib.crc32(payload) != crc:
        raise WireError("crc mismatch")
    if kind & KIND_COMPRESSED:
        kind &= ~KIND_COMPRESSED
        payload = wire_mod.bounded_decompress(payload, MAX_PAYLOAD)
    return kind, payload


class _TCPConnection(IConnection):
    def __init__(self, sock, owner: "TCPTransport", target: str):
        self._sock = sock
        self._owner = owner
        self._target = target
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def send_message_batch(self, batch: MessageBatch) -> None:
        inj = self._owner.fault_injector
        if inj is None:
            batches = (batch,)
        else:
            # fault identity is the RAFT address (what plans target),
            # not the bind address — they differ under ListenAddress
            # overrides and port-0 binds
            src = self._owner.fault_source or self._owner.listen_address
            batches = inj.on_wire(src, self._target, batch)
        with self._lock:
            for b in batches:
                _write_frame(self._sock, KIND_BATCH, encode_batch(b))


class _TCPSnapshotConnection(ISnapshotConnection):
    def __init__(self, sock, owner: "TCPTransport", target: str):
        self._sock = sock
        self._owner = owner
        self._target = target
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def query_resume(self, probe: Chunk) -> int:
        """Resume-cursor exchange on the (otherwise write-only) snapshot
        socket: one KIND_RESUME_QUERY frame out, one KIND_RESUME_RESP
        frame back.  Any failure (old receiver closing on the unknown
        kind, timeout, torn connection) degrades to 0 — the sender
        restarts from chunk 0 and the receiver's idempotent re-delivery
        discards what it already holds."""
        try:
            with self._lock:
                _write_frame(
                    self._sock, KIND_RESUME_QUERY, encode_chunk(probe)
                )
                frame = _read_frame(self._sock)
            if frame is None:
                return 0
            kind, payload = frame
            if kind != KIND_RESUME_RESP or len(payload) != 8:
                return 0
            return struct.unpack("<Q", payload)[0]
        except (OSError, WireError, ValueError):
            return 0

    def send_chunk(self, chunk: Chunk) -> None:
        inj = self._owner.fault_injector
        if inj is None:
            chunks = (chunk,)
        else:
            src = self._owner.fault_source or self._owner.listen_address
            chunks = inj.on_wire(src, self._target, chunk)
        with self._lock:
            for c in chunks:
                _write_frame(self._sock, KIND_CHUNK, encode_chunk(c))
        if not chunks:
            # see the inproc chunk lane: a swallowed chunk must fail the
            # send, or the sender's raft peer wedges in SNAPSHOT state
            raise ConnectionError("nemesis: snapshot chunk lost")


class TCPTransport(ITransport):
    """reference: NewTCPTransport [U]."""

    def __init__(
        self,
        listen_address: str,
        message_handler: MessageHandler,
        chunk_handler: Optional[ChunkHandler] = None,
        *,
        ssl_server_ctx: Optional[ssl.SSLContext] = None,
        ssl_client_ctx: Optional[ssl.SSLContext] = None,
        connect_timeout: float = 5.0,
    ):
        self.listen_address = listen_address
        self.message_handler = message_handler
        self.chunk_handler = chunk_handler
        self._ssl_server_ctx = ssl_server_ctx
        self._ssl_client_ctx = ssl_client_ctx
        self._connect_timeout = connect_timeout
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads = []
        self._conn_lock = threading.Lock()
        self._inbound = set()
        # the unified fault plane, same contract as the in-proc
        # transport (faults.FaultController.on_wire)
        self.fault_injector = None
        # resume-cursor query target (ChunkSink.resume_cursor); set by
        # the NodeHost beside chunk_handler
        self.resume_handler = None

    def name(self) -> str:
        return "tcp"

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        host, port = parse_address(self.listen_address)
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(128)
        ls.settimeout(0.2)
        self._listener = ls
        # the OS may have assigned an ephemeral port (tests use port 0)
        self.listen_address = f"{host}:{ls.getsockname()[1]}"
        t = threading.Thread(
            target=self._accept_main, daemon=True, name="tpu-raft-tcp-accept"
        )
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            for s in list(self._inbound):
                try:
                    s.close()
                except OSError:
                    pass
            self._inbound.clear()
        for t in self._threads:
            t.join(timeout=1.0)

    # -- outbound --------------------------------------------------------
    def _connect(self, target: str):
        host, port = parse_address(target)
        sock = socket.create_connection(
            (host, port), timeout=self._connect_timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(30.0)
        if self._ssl_client_ctx is not None:
            sock = self._ssl_client_ctx.wrap_socket(sock, server_hostname=host)
        return sock

    def get_connection(self, target: str) -> IConnection:
        return _TCPConnection(self._connect(target), self, target)

    def get_snapshot_connection(self, target: str) -> ISnapshotConnection:
        return _TCPSnapshotConnection(self._connect(target), self, target)

    # -- inbound ---------------------------------------------------------
    def _accept_main(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._ssl_server_ctx is not None:
                try:
                    sock = self._ssl_server_ctx.wrap_socket(
                        sock, server_side=True
                    )
                except (ssl.SSLError, OSError) as e:
                    _log.warning("tls handshake failed: %s", e)
                    try:
                        sock.close()  # else each failed handshake leaks a fd
                    except OSError:
                        pass
                    continue
            with self._conn_lock:
                self._inbound.add(sock)
            t = threading.Thread(
                target=self._reader_main,
                args=(sock,),
                daemon=True,
                name="tpu-raft-tcp-reader",
            )
            t.start()

    def _reader_main(self, sock) -> None:
        try:
            while not self._stop.is_set():
                frame = _read_frame(sock)
                if frame is None:
                    return
                kind, payload = frame
                if kind == KIND_BATCH:
                    self.message_handler(decode_batch(payload))
                elif kind == KIND_CHUNK:
                    if self.chunk_handler is not None and not self.chunk_handler(
                        decode_chunk(payload)
                    ):
                        # rejected chunk (out-of-order / failed receive):
                        # tear the connection down so the sending stream
                        # job fails fast and retries/reports, instead of
                        # pumping the rest of a doomed stream
                        raise WireError("chunk rejected by receiver")
                elif kind == KIND_RESUME_QUERY:
                    cursor = 0
                    if self.resume_handler is not None:
                        cursor = self.resume_handler(decode_chunk(payload))
                    _write_frame(
                        sock, KIND_RESUME_RESP,
                        struct.pack("<Q", cursor & MASK64),
                    )
                else:
                    raise WireError(f"unknown frame kind {kind}")
        except (WireError, ValueError) as e:
            _log.warning("closing connection on bad frame: %s", e)
        except OSError:
            pass
        finally:
            with self._conn_lock:
                self._inbound.discard(sock)
            try:
                sock.close()
            except OSError:
                pass


def tcp_transport_factory(config, message_handler, chunk_handler):
    """NodeHostConfig.expert.transport_factory hook.

    `config.raft_address` must be "host:port"; `listen_address`
    overrides the bind address (reference: NodeHostConfig
    ListenAddress [U]).  With `mutual_tls`, `ca_file`/`cert_file`/
    `key_file` configure both peers' contexts.
    """
    server_ctx = client_ctx = None
    if getattr(config, "mutual_tls", False):
        server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        server_ctx.verify_mode = ssl.CERT_REQUIRED
        server_ctx.load_cert_chain(config.cert_file, config.key_file)
        server_ctx.load_verify_locations(config.ca_file)
        client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        client_ctx.load_cert_chain(config.cert_file, config.key_file)
        client_ctx.load_verify_locations(config.ca_file)
        client_ctx.check_hostname = False
    return TCPTransport(
        config.get_listen_address(),
        message_handler,
        chunk_handler,
        ssl_server_ctx=server_ctx,
        ssl_client_ctx=client_ctx,
    )
