"""Replica address resolution.

reference: internal/registry/registry.go (static Registry) [U].  Maps
(shard_id, replica_id) -> target address.  The gossip-based registry
(AddressByNodeHostID mode) plugs in behind the same resolve() interface.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..id import is_nodehost_id


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._addr: Dict[Tuple[int, int], str] = {}

    def add(self, shard_id: int, replica_id: int, address: str) -> None:
        with self._lock:
            self._addr[(shard_id, replica_id)] = address

    def learn(self, shard_id: int, replica_id: int, address: str) -> None:
        """Learn a sender's return address from observed traffic.  Unlike
        ``add`` this never replaces a NodeHostID mapping with a literal
        raft address — doing so would pin the peer to its current host
        and defeat the gossip indirection until the next membership sync."""
        with self._lock:
            cur = self._addr.get((shard_id, replica_id))
            if cur is not None and is_nodehost_id(cur):
                return
            self._addr[(shard_id, replica_id)] = address

    def remove(self, shard_id: int, replica_id: int) -> None:
        with self._lock:
            self._addr.pop((shard_id, replica_id), None)

    def remove_shard(self, shard_id: int) -> None:
        with self._lock:
            for k in [k for k in self._addr if k[0] == shard_id]:
                del self._addr[k]

    def resolve(self, shard_id: int, replica_id: int) -> Optional[str]:
        with self._lock:
            return self._addr.get((shard_id, replica_id))
