"""Gossip registry: NodeHostID -> RaftAddress resolution over UDP.

reference: internal/registry gossip mode (hashicorp/memberlist
propagating NodeHostID->RaftAddress so replicas can move hosts) [U].
This is a push-gossip epidemic: every interval each node sends its full
(id, address, version) table to up to ``fanout`` random known peers
plus the configured seeds; receivers merge by per-origin version.  The
table is tiny (one row per nodehost), so full-state push keeps the
protocol trivially convergent without anti-entropy digests.

``GossipRegistry`` wraps the static (shard, replica) -> value registry:
when the stored value is a NodeHostID the gossip table translates it to
the host's current raft address at resolve time.
"""
from __future__ import annotations

import random
import socket
import struct
import threading
from io import BytesIO
from typing import Dict, List, Optional, Tuple

from ..id import is_nodehost_id
from ..logger import get_logger
from ..pb import MASK64
from .registry import Registry
from .tcp import parse_address

_log = get_logger("registry")

_MAGIC = 0x47535052  # "GSPR"
_u32 = struct.Struct("<I")
_u64 = struct.Struct("<Q")

MAX_PACKET = 60 * 1024
MAX_ROWS = 4096  # per-packet row cap, enforced symmetrically encode/decode
# per-string bound (ids are ~36B uuids, addrs host:port): keeps any single
# accepted row far below MAX_PACKET so _encode_packets' per-packet size
# invariant can't be broken by a hostile row that got merged into the table
MAX_ROW_STR = 512


def _encode_row(nhid: str, addr: str, ver: int) -> bytes:
    b = BytesIO()
    for s in (nhid, addr):
        raw = s.encode("utf-8")
        b.write(_u32.pack(len(raw)))
        b.write(raw)
    b.write(_u64.pack(ver & MASK64))
    return b.getvalue()


def _encode_packets(
    table: Dict[str, Tuple[str, int]], sender: str, sender_id: str = ""
) -> List[bytes]:
    """Shard the full table into UDP-safe packets (each under MAX_PACKET
    and under the decoder's 4096-row cap).  Every packet carries the
    ``__sender__`` row so receivers learn the peer address from any
    fragment, plus the ``__sender_id__`` row (the origin's NodeHostID)
    so receivers can track per-host liveness from DIRECT contact — a
    relayed row about X says nothing about X being alive; a packet FROM
    X does.  Merge is per-row, so fragments need no reassembly."""
    meta_rows = [_encode_row("__sender__", sender, 0)]
    if sender_id:
        meta_rows.append(_encode_row("__sender_id__", sender_id, 0))
    meta_size = sum(len(r) for r in meta_rows)
    rows: List[List[bytes]] = [list(meta_rows)]
    size = 8 + meta_size
    for nhid, (addr, ver) in table.items():
        if len(nhid.encode()) > MAX_ROW_STR or len(addr.encode()) > MAX_ROW_STR:
            continue  # decoder would reject it anyway; don't waste a packet
        rb = _encode_row(nhid, addr, ver)
        if size + len(rb) > MAX_PACKET or len(rows[-1]) >= MAX_ROWS:
            rows.append(list(meta_rows))
            size = 8 + meta_size
        rows[-1].append(rb)
        size += len(rb)
    return [
        _u32.pack(_MAGIC) + _u32.pack(len(chunk)) + b"".join(chunk)
        for chunk in rows
    ]


def _decode_table(data: bytes) -> Optional[Dict[str, Tuple[str, int]]]:
    try:
        pos = 0

        def take(n):
            nonlocal pos
            if pos + n > len(data):
                raise ValueError("short")
            out = data[pos : pos + n]
            pos += n
            return out

        if _u32.unpack(take(4))[0] != _MAGIC:
            return None
        count = _u32.unpack(take(4))[0]
        if count > MAX_ROWS:
            return None
        table = {}
        for _ in range(count):
            n1 = _u32.unpack(take(4))[0]
            if n1 > MAX_ROW_STR:
                return None
            nhid = take(n1).decode("utf-8")
            n2 = _u32.unpack(take(4))[0]
            if n2 > MAX_ROW_STR:
                return None
            addr = take(n2).decode("utf-8")
            ver = _u64.unpack(take(8))[0]
            table[nhid] = (addr, ver)
        return table
    except (ValueError, UnicodeDecodeError, struct.error):
        return None


# consecutive direct packets a suspect peer must deliver before it
# counts alive again (see GossipManager._suspect)
SUSPECT_CLEAR_PACKETS = 3


class GossipManager:
    """The UDP push-gossip epidemic itself."""

    def __init__(
        self,
        nodehost_id: str,
        raft_address: str,
        bind_address: str,
        seeds: List[str],
        advertise_address: str = "",
        interval: float = 0.2,
        fanout: int = 3,
    ):
        self.nodehost_id = nodehost_id
        self.raft_address = raft_address
        self.bind_address = bind_address
        self.advertise_address = advertise_address
        self.seeds = list(seeds)
        self.interval = interval
        self.fanout = fanout
        self._lock = threading.Lock()
        # nodehost_id -> (raft_address, version)
        self._table: Dict[str, Tuple[str, int]] = {nodehost_id: (raft_address, 1)}
        # gossip peer addresses we have heard from (for fanout selection)
        self._peers: set = set(seeds)
        # nodehost_id -> monotonic instant of last DIRECT packet from it
        # (liveness for the balance control plane; relayed rows don't
        # count — see _encode_packets)
        self._last_heard: Dict[str, float] = {}
        # suspect hysteresis (docs/BALANCE.md, one-way partitions): a
        # peer that ever misses its liveness window is SUSPECT and must
        # deliver SUSPECT_CLEAR_PACKETS consecutive direct packets
        # before it reads alive again.  Under an intermittent
        # asym_drop toward us (p < 1) the occasional lucky packet
        # refreshes _last_heard sporadically — without the counter the
        # peer's liveness would oscillate at the window boundary and
        # the balance repair invariant would churn its replicas.
        # nodehost_id -> direct packets heard since marked suspect
        self._suspect: Dict[str, int] = {}
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._send_err_logged = False

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        host, port = parse_address(self.bind_address)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.settimeout(0.2)
        self._sock = s
        self.bind_address = f"{host}:{s.getsockname()[1]}"
        if not self.advertise_address:
            self.advertise_address = self.bind_address
        for fn, name in (
            (self._recv_main, "gossip-recv"),
            (self._push_main, "gossip-push"),
        ):
            t = threading.Thread(target=fn, daemon=True, name=f"tpu-raft-{name}")
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        if self._sock is not None:
            self._sock.close()

    # -- api -------------------------------------------------------------
    def set_raft_address(self, addr: str) -> None:
        """Advertise a new raft address (host moved)."""
        with self._lock:
            _, ver = self._table[self.nodehost_id]
            self._table[self.nodehost_id] = (addr, ver + 1)
            self.raft_address = addr

    def lookup(self, nodehost_id: str) -> Optional[str]:
        with self._lock:
            rec = self._table.get(nodehost_id)
            return rec[0] if rec else None

    def table(self) -> Dict[str, str]:
        with self._lock:
            return {k: v[0] for k, v in self._table.items()}

    def last_heard(self, nodehost_id: str) -> Optional[float]:
        """Monotonic instant of the last packet received directly from
        the host, or None if never heard (self counts as now)."""
        import time as _time

        if nodehost_id == self.nodehost_id:
            return _time.monotonic()
        with self._lock:
            return self._last_heard.get(nodehost_id)

    def alive_peers(self, window: Optional[float] = None) -> set:
        """NodeHostIDs heard from directly within ``window`` seconds
        (always includes self).  The balance collector's liveness
        signal when hosts span processes.

        The default window scales with fleet size: each push round
        targets only ``fanout`` random peers (plus the seeds), so with
        N hosts the expected gap between DIRECT contacts from a given
        live peer is ~``interval * N / fanout`` — a fixed small window
        would mark live hosts dead at moderate fleet sizes and the
        balance repair invariant would churn their replicas.  Pass an
        explicit window only with that math in mind."""
        import time as _time

        if window is None:
            with self._lock:
                n = max(len(self._table), 1)
            window = max(2.0, self.interval * 5.0 * n / max(self.fanout, 1))
        cutoff = _time.monotonic() - window
        with self._lock:
            alive = set()
            for k, t in self._last_heard.items():
                if t < cutoff:
                    # missed the window: suspect from here on — reset
                    # the recovery counter even if already suspect
                    self._suspect[k] = 0
                    continue
                if k in self._suspect:
                    # fresh but still suspect: one lucky packet through
                    # an intermittent one-way drop is not recovery
                    continue
                alive.add(k)
        alive.add(self.nodehost_id)
        return alive

    # -- internals -------------------------------------------------------
    def _merge(self, table: Dict[str, Tuple[str, int]], sender,
               sender_id: Optional[str] = None) -> None:
        import time as _time

        with self._lock:
            if sender_id:
                self._last_heard[sender_id] = _time.monotonic()
                if sender_id in self._suspect:
                    self._suspect[sender_id] += 1
                    if self._suspect[sender_id] >= SUSPECT_CLEAR_PACKETS:
                        del self._suspect[sender_id]
            for nhid, (addr, ver) in table.items():
                if nhid == self.nodehost_id:
                    # never accept a peer's view of OUR address: after a
                    # restart peers gossip the old address at a higher
                    # version; refute it by re-asserting ours above it
                    cur_addr, cur_ver = self._table[nhid]
                    if ver >= cur_ver and addr != cur_addr:
                        self._table[nhid] = (cur_addr, ver + 1)
                    continue
                cur = self._table.get(nhid)
                if cur is None or ver > cur[1]:
                    self._table[nhid] = (addr, ver)
            if sender:
                self._peers.add(sender)

    def _recv_main(self) -> None:
        while not self._stop.is_set():
            try:
                data, addr = self._sock.recvfrom(MAX_PACKET)
            except socket.timeout:
                continue
            except OSError:
                return
            table = _decode_table(data)
            if table is None:
                continue
            # the packet's meta rows carry the sender's gossip addr and
            # NodeHostID (the liveness signal)
            sender = table.pop("__sender__", None)
            sender_id = table.pop("__sender_id__", None)
            self._merge(
                table,
                sender[0] if sender else None,
                sender_id[0] if sender_id else None,
            )

    def _push_main(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.interval)
            if self._stop.is_set():
                return
            with self._lock:
                table = dict(self._table)
                peers = list(self._peers)
            pkts = _encode_packets(table, self.advertise_address, self.nodehost_id)
            random.shuffle(peers)
            targets = peers[: self.fanout]
            for seed in self.seeds:
                if seed not in targets:
                    targets.append(seed)
            for t in targets:
                if t == self.advertise_address:
                    continue
                for pkt in pkts:
                    try:
                        self._sock.sendto(pkt, parse_address(t))
                    except OSError as e:
                        if not self._send_err_logged:
                            self._send_err_logged = True
                            _log.warning(
                                "gossip sendto %s failed (%s); "
                                "further send errors suppressed", t, e
                            )


class GossipRegistry(Registry):
    """(shard, replica) -> address registry that resolves NodeHostIDs
    through the gossip table (reference: INodeRegistry gossip mode [U])."""

    def __init__(self, manager: GossipManager):
        super().__init__()
        self.manager = manager

    def resolve(self, shard_id: int, replica_id: int) -> Optional[str]:
        v = super().resolve(shard_id, replica_id)
        if v is not None and is_nodehost_id(v):
            return self.manager.lookup(v)
        return v
