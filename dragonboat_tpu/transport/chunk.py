"""Snapshot chunking: split on the sender, reassemble on the receiver.

reference: internal/transport/chunk.go (splitSnapshotMessage, Chunk.Add)
[U].  A snapshot never travels as one message: the sender reads the
snapshot payload ONCE (synchronously, while the file is guaranteed live)
and streams fixed-size chunks over the snapshot lane; the receiver
reassembles them into its OWN local snapshot storage and only then
injects the InstallSnapshot message into the raft path.  Replicas never
share snapshot files by path — each host owns its copy, exactly as the
reference's chunk protocol guarantees.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from .. import settings
from ..logger import get_logger
from ..pb import Chunk, Message, MessageType, Snapshot

_log = get_logger("transport")


def split_snapshot_message(
    m: Message, payload: bytes, chunk_size: Optional[int] = None
) -> List[Chunk]:
    """Split an InstallSnapshot message + its payload into wire chunks
    (reference: splitSnapshotMessage [U])."""
    ss = m.snapshot
    size = chunk_size or settings.Soft.snapshot_chunk_size
    if ss.dummy or not payload:
        pieces = [b""]
    else:
        pieces = [payload[i : i + size] for i in range(0, len(payload), size)]
    count = len(pieces)
    return [
        Chunk(
            shard_id=m.shard_id,
            replica_id=m.to,
            from_=m.from_,
            chunk_id=i,
            chunk_size=len(piece),
            chunk_count=count,
            index=ss.index,
            term=ss.term,
            message_term=m.term,
            data=piece,
            membership=ss.membership,
            filepath=ss.filepath,
            file_size=len(payload),
            witness=ss.witness,
            dummy=ss.dummy,
            on_disk_index=ss.on_disk_index,
        )
        for i, piece in enumerate(pieces)
    ]


class _InFlight:
    __slots__ = ("pieces", "next_chunk", "count", "ident")

    def __init__(self, count: int, ident: tuple):
        self.pieces: List[bytes] = []
        self.next_chunk = 0
        self.count = count
        # stream identity: every chunk of one stream must agree on these,
        # otherwise two interleaved streams from the same sender could
        # splice into one corrupted payload (reference: Chunk.Add validates
        # non-leading chunks against the in-flight record [U])
        self.ident = ident


def _chunk_ident(c: Chunk) -> tuple:
    return (c.index, c.term, c.message_term, c.chunk_count, c.file_size, c.filepath)


class ChunkSink:
    """Receiver-side reassembly, one in-flight snapshot per (shard, sender)
    (reference: transport.Chunk tracking in-flight state per key [U]).

    ``save_fn(shard_id, replica_id, index, payload) -> filepath`` persists
    into the receiver's local snapshot storage; ``deliver_fn(message)``
    hands the reconstituted InstallSnapshot to the raft path;
    ``confirm_fn(shard_id, from_replica, to_replica)`` sends
    SnapshotReceived back to the sender.
    """

    def __init__(
        self,
        save_fn: Callable[[int, int, int, bytes], str],
        deliver_fn: Callable[[Message], None],
        confirm_fn: Optional[Callable[[int, int, int], None]] = None,
    ):
        self.save_fn = save_fn
        self.deliver_fn = deliver_fn
        self.confirm_fn = confirm_fn
        self._lock = threading.Lock()
        self._inflight: Dict[Tuple[int, int], _InFlight] = {}

    def add(self, c: Chunk) -> bool:
        """Accept one chunk; returns False to make the sender abort the
        stream (out-of-order / mismatched chunk)."""
        key = (c.shard_id, c.from_)
        with self._lock:
            fl = self._inflight.get(key)
            if c.chunk_id == 0:
                fl = _InFlight(c.chunk_count, _chunk_ident(c))
                self._inflight[key] = fl
            elif (
                fl is None
                or c.chunk_id != fl.next_chunk
                or _chunk_ident(c) != fl.ident
            ):
                _log.warning(
                    "out-of-order/mismatched chunk %d for shard %d from %d",
                    c.chunk_id,
                    c.shard_id,
                    c.from_,
                )
                self._inflight.pop(key, None)
                return False
            fl.pieces.append(c.data)
            fl.next_chunk = c.chunk_id + 1
            done = fl.next_chunk == fl.count
            if done:
                self._inflight.pop(key, None)
        if done:
            self._complete(c, b"".join(fl.pieces))
        return True

    def _complete(self, last: Chunk, payload: bytes) -> None:
        if last.dummy:
            filepath = ""
        else:
            filepath = self.save_fn(
                last.shard_id, last.replica_id, last.index, payload
            )
        ss = Snapshot(
            filepath=filepath,
            file_size=last.file_size,
            index=last.index,
            term=last.term,
            membership=last.membership,
            dummy=last.dummy,
            witness=last.witness,
            shard_id=last.shard_id,
            replica_id=last.replica_id,
            on_disk_index=last.on_disk_index,
        )
        self.deliver_fn(
            Message(
                type=MessageType.INSTALL_SNAPSHOT,
                shard_id=last.shard_id,
                from_=last.from_,
                to=last.replica_id,
                term=last.message_term,
                snapshot=ss,
            )
        )
        if self.confirm_fn is not None:
            self.confirm_fn(last.shard_id, last.from_, last.replica_id)
