"""Snapshot chunking: split on the sender, reassemble on the receiver.

reference: internal/transport/chunk.go (splitSnapshotMessage, Chunk.Add)
[U].  A snapshot never travels as one message: the sender's stream job
reads the container INCREMENTALLY (one chunk in memory at a time, under
a storage GC lease) and streams fixed-size chunks over the snapshot
lane; the receiver writes each chunk to its OWN local snapshot storage
as it lands (bounded memory on both sides) and only then injects the
InstallSnapshot message into the raft path.  External files
(ISnapshotFileCollection) travel as additional chunk sequences tagged
with ``has_file_info``, exactly like the reference's file chunks.
Replicas never share snapshot files by path — each host owns its copy.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from .. import settings
from ..logger import get_logger
from ..pb import Chunk, Message, MessageType, Snapshot, SnapshotFile

_log = get_logger("transport")


def _stream_geometry(m: Message, source, size: int):
    """(total_chunks, main_size, files) for one stream — shared by the
    chunk iterator and the sender's resume probe so both agree on the
    stream identity fields byte-for-byte."""
    ss = m.snapshot
    if ss.dummy:
        return 1, 0, []

    def n_chunks(nbytes: int) -> int:
        return max(1, -(-nbytes // size))

    files: List[Tuple[SnapshotFile, str]] = source.externals
    main_size = source.main_size
    total = n_chunks(main_size) + sum(
        n_chunks(sf.file_size) for sf, _ in files
    )
    return total, main_size, files


def resume_probe(m: Message, source, chunk_size: Optional[int] = None) -> Chunk:
    """A data-less chunk carrying one stream's identity, for
    ``ISnapshotConnection.query_resume``: the receiver matches it
    against its in-flight record's ``_chunk_ident`` and answers with
    its receive cursor (the next chunk offset it needs).  The cursor is
    keyed by snapshot index + chunk geometry, so a resumed sender can
    only continue the SAME immutable payload it was sending."""
    ss = m.snapshot
    size = chunk_size or settings.Soft.snapshot_chunk_size
    total, main_size, _files = _stream_geometry(m, source, size)
    return Chunk(
        shard_id=m.shard_id,
        replica_id=m.to,
        from_=m.from_,
        chunk_count=total,
        index=ss.index,
        term=ss.term,
        message_term=m.term,
        membership=ss.membership,
        filepath=ss.filepath,
        file_size=main_size,
        witness=ss.witness,
        dummy=ss.dummy,
        on_disk_index=ss.on_disk_index,
    )


def iter_snapshot_chunks(
    m: Message, source, chunk_size: Optional[int] = None,
    start_chunk: int = 0,
) -> Iterator[Chunk]:
    """Lazily yield the wire chunks for an InstallSnapshot message.

    ``source`` is a ``SnapshotSource`` (storage/snapshotter.py): main
    container + external files, read incrementally so only one chunk is
    ever materialized (reference: splitSnapshotMessage + job.go
    incremental reads [U]).  ``source`` must stay open for the duration.

    ``start_chunk`` resumes a partially-delivered stream: chunks below
    it are neither read nor sent (the main container is seeked past;
    fully-delivered external files are never opened).  Chunk ``k`` of a
    given (index, term, geometry) is a fixed byte range of immutable
    snapshot files, so a resumed iteration yields byte-identical chunks.
    """
    ss = m.snapshot
    size = chunk_size or settings.Soft.snapshot_chunk_size

    def n_chunks(nbytes: int) -> int:
        return max(1, -(-nbytes // size))

    total, main_size, files = _stream_geometry(m, source, size)

    def base(i: int, piece: bytes, **kw) -> Chunk:
        return Chunk(
            shard_id=m.shard_id,
            replica_id=m.to,
            from_=m.from_,
            chunk_id=i,
            chunk_size=len(piece),
            chunk_count=total,
            index=ss.index,
            term=ss.term,
            message_term=m.term,
            data=piece,
            membership=ss.membership,
            filepath=ss.filepath,
            file_size=main_size,
            witness=ss.witness,
            dummy=ss.dummy,
            on_disk_index=ss.on_disk_index,
            **kw,
        )

    if ss.dummy:
        if start_chunk == 0:
            yield base(0, b"")
        return

    mcount = n_chunks(main_size)
    cid = 0
    if start_chunk < mcount:
        with source.open_main() as f:
            sent = 0
            if start_chunk:
                f.seek(start_chunk * size)
                cid = start_chunk
                sent = start_chunk * size
            while True:
                piece = f.read(size)
                if not piece and sent > 0:
                    break
                yield base(cid, piece)
                cid += 1
                sent += len(piece)
                if not piece:
                    break
    else:
        cid = mcount
    for sf, path in files:
        fcount = n_chunks(sf.file_size)
        if start_chunk >= cid + fcount:
            cid += fcount  # file fully delivered before the resume point
            continue
        with source.open_external(path) as f:
            fcid = 0
            if start_chunk > cid:
                fcid = start_chunk - cid
                f.seek(fcid * size)
                cid = start_chunk
            while True:
                piece = f.read(size)
                if not piece and fcid > 0:
                    break
                yield base(
                    cid,
                    piece,
                    has_file_info=True,
                    file_info=sf,
                    file_chunk_id=fcid,
                    file_chunk_count=fcount,
                )
                cid += 1
                fcid += 1
                if not piece:
                    break


def split_snapshot_message(
    m: Message, payload: bytes, chunk_size: Optional[int] = None
) -> List[Chunk]:
    """Split an in-memory payload into wire chunks (tests and the
    in-proc fast path; the production sender uses iter_snapshot_chunks)."""

    class _BytesSource:
        main_size = len(payload)
        externals: List[Tuple[SnapshotFile, str]] = []

        def open_main(self):
            import io

            return io.BytesIO(payload)

        def open_external(self, path):  # pragma: no cover - no externals
            raise FileNotFoundError(path)

    return list(iter_snapshot_chunks(m, _BytesSource(), chunk_size))


class _InFlight:
    __slots__ = (
        "sink", "next_chunk", "count", "ident", "cur_file", "pending_open",
    )

    def __init__(self, count: int, ident: tuple, sink):
        self.sink = sink  # None for dummy snapshots
        self.pending_open = False
        self.next_chunk = 0
        self.count = count
        self.cur_file = None  # file_id currently being written
        # stream identity: every chunk of one stream must agree on these,
        # otherwise two interleaved streams from the same sender could
        # splice into one corrupted payload (reference: Chunk.Add validates
        # non-leading chunks against the in-flight record [U])
        self.ident = ident


def _chunk_ident(c: Chunk) -> tuple:
    return (c.index, c.term, c.message_term, c.chunk_count, c.file_size, c.filepath)


class ChunkSink:
    """Receiver-side reassembly, one in-flight snapshot per (shard, sender)
    (reference: transport.Chunk tracking in-flight state per key [U]).

    ``begin_fn(shard_id, replica_id, index) -> sink`` opens an
    incremental receive sink in local snapshot storage (``write``,
    ``begin_external``, ``finalize() -> filepath``, ``abort``);
    ``deliver_fn(message)`` hands the reconstituted InstallSnapshot to
    the raft path; ``confirm_fn(shard_id, from_replica, to_replica)``
    sends SnapshotReceived back to the sender.
    """

    def __init__(
        self,
        begin_fn: Callable[[int, int, int], object],
        deliver_fn: Callable[[Message], None],
        confirm_fn: Optional[Callable[[int, int, int], None]] = None,
        reject_fn: Optional[Callable[[int, int, int], None]] = None,
    ):
        self.begin_fn = begin_fn
        self.deliver_fn = deliver_fn
        self.confirm_fn = confirm_fn
        # a completed stream whose container fails validation (corrupt
        # payload survived the wire): tell the sender so its raft peer
        # clears the pending snapshot and retries
        self.reject_fn = reject_fn
        self._lock = threading.Lock()
        self._inflight: Dict[Tuple[int, int], _InFlight] = {}

    def resume_cursor(self, probe: Chunk) -> int:
        """The receive cursor for a stream matching ``probe``'s identity
        (``transport.chunk.resume_probe``): the next chunk offset this
        receiver needs, or 0 when no matching in-flight stream exists
        (restart from scratch).  Chunks below the cursor are already on
        local disk; a reconnected sender skips them entirely — the
        resume half of the resumable-stream protocol (docs/BIGSTATE.md).
        """
        with self._lock:
            fl = self._inflight.get((probe.shard_id, probe.from_))
            if fl is not None and fl.ident == _chunk_ident(probe):
                return fl.next_chunk
        return 0

    def add(self, c: Chunk) -> bool:
        """Accept one chunk; returns False to make the sender abort the
        stream (out-of-order / mismatched chunk).

        The lock only guards the in-flight MAP: all disk I/O (sink open,
        writes, the per-file fsync at external boundaries) runs outside
        it, so concurrent inbound streams from different senders never
        queue behind each other's fsyncs.  Per-stream fields of one
        ``_InFlight`` are touched only by its delivering connection
        thread; a superseding chunk 0 swaps the map entry under the lock
        and aborts the old sink outside it.
        """
        key = (c.shard_id, c.from_)
        stale = None
        with self._lock:
            fl = self._inflight.get(key)
            if (
                fl is not None
                and _chunk_ident(c) == fl.ident
                and c.chunk_id < fl.next_chunk
            ):
                # idempotent re-delivery of an already-written offset: a
                # reconnected sender restarting below the receive cursor
                # (no resume support, or an overlapping resume) re-sends
                # bytes that are already on local disk — chunk k of one
                # identity is a fixed range of an immutable snapshot, so
                # accept-and-discard is safe, and rejecting would burn
                # the whole transfer back to zero (the pre-fix behavior)
                return True
            if c.chunk_id == 0:
                stale = fl
                fl = _InFlight(c.chunk_count, _chunk_ident(c), None)
                fl.pending_open = not c.dummy
                self._inflight[key] = fl
            elif (
                fl is None
                or c.chunk_id != fl.next_chunk
                or _chunk_ident(c) != fl.ident
            ):
                stale = self._inflight.pop(key, None)
                fl = None
        if stale is not None and stale.sink is not None:
            stale.sink.abort()
        if fl is None:
            _log.warning(
                "out-of-order/mismatched chunk %d for shard %d from %d",
                c.chunk_id,
                c.shard_id,
                c.from_,
            )
            return False
        try:
            if fl.pending_open:
                fl.pending_open = False
                fl.sink = self.begin_fn(c.shard_id, c.replica_id, c.index)
            if fl.sink is not None:
                if c.has_file_info and fl.cur_file != c.file_info.file_id:
                    fl.cur_file = c.file_info.file_id
                    fl.sink.begin_external(c.file_info.filepath)
                fl.sink.write(c.data)
        except Exception as e:  # noqa: BLE001 - disk trouble
            _log.warning("receive sink failed: %s", e)
            if fl.sink is not None:
                fl.sink.abort()
            with self._lock:
                if self._inflight.get(key) is fl:
                    del self._inflight[key]
            # tell the SENDER over the wire, like the validation-failure
            # path in _complete: on transports where the send is
            # buffered (TCP) the returned False never reaches the
            # sending stream job, and without the reject the leader's
            # raft remote would wedge in SNAPSHOT state forever
            if self.reject_fn is not None:
                self.reject_fn(c.shard_id, c.from_, c.replica_id)
            return False
        fl.next_chunk = c.chunk_id + 1
        done = fl.next_chunk == fl.count
        if done:
            with self._lock:
                if self._inflight.get(key) is fl:
                    del self._inflight[key]
            # a corrupt/unfinalizable stream returns False for the LAST
            # chunk: the sending stream job sees a failed send and runs
            # its retry/report path instead of assuming delivery
            return self._complete(c, fl)
        return True

    def _complete(self, last: Chunk, fl: _InFlight) -> bool:
        if fl.sink is None:
            filepath = ""
        else:
            validate = getattr(fl.sink, "validate", None)
            if validate is not None:
                try:
                    validate()
                except Exception as e:  # noqa: BLE001 - corrupt container
                    _log.warning(
                        "received snapshot for shard %d from %d failed "
                        "validation, discarding: %s",
                        last.shard_id, last.from_, e,
                    )
                    fl.sink.abort()
                    if self.reject_fn is not None:
                        self.reject_fn(last.shard_id, last.from_, last.replica_id)
                    return False
            try:
                filepath = fl.sink.finalize()
            except Exception as e:  # noqa: BLE001 - disk trouble
                _log.warning("receive sink finalize failed: %s", e)
                fl.sink.abort()
                if self.reject_fn is not None:
                    self.reject_fn(last.shard_id, last.from_, last.replica_id)
                return False
        ss = Snapshot(
            filepath=filepath,
            file_size=last.file_size,
            index=last.index,
            term=last.term,
            membership=last.membership,
            dummy=last.dummy,
            witness=last.witness,
            shard_id=last.shard_id,
            replica_id=last.replica_id,
            on_disk_index=last.on_disk_index,
        )
        self.deliver_fn(
            Message(
                type=MessageType.INSTALL_SNAPSHOT,
                shard_id=last.shard_id,
                from_=last.from_,
                to=last.replica_id,
                term=last.message_term,
                snapshot=ss,
            )
        )
        if self.confirm_fn is not None:
            self.confirm_fn(last.shard_id, last.from_, last.replica_id)
        return True
