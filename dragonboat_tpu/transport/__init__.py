"""Communication layer (reference: internal/transport/ [U])."""
from .inproc import InProcTransport, reset_inproc_network
from .registry import Registry
from .transport import Transport

__all__ = ["InProcTransport", "reset_inproc_network", "Registry", "Transport"]
