"""In-process loopback transport.

reference: the chan-based test transports in internal/transport [U].
Multiple NodeHosts in one process register by address in a module-level
network table; delivery is a direct call into the receiver's handler
(which only enqueues — cheap and deadlock-free).  Fault injection goes
through the unified ``fault_injector`` hook protocol
(faults.FaultController.on_wire): partitions, drop/delay/duplicate/
reorder and chunk corruption, shared with the TCP transport.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ..pb import Chunk, MessageBatch
from ..raftio import (
    ChunkHandler,
    IConnection,
    ISnapshotConnection,
    ITransport,
    MessageHandler,
)

_network_lock = threading.Lock()
_network: Dict[str, "InProcTransport"] = {}


def reset_inproc_network() -> None:
    with _network_lock:
        _network.clear()


class _InProcConnection(IConnection):
    def __init__(self, owner: "InProcTransport", target: str):
        self.owner = owner
        self.target = target

    def close(self) -> None:
        pass

    def send_message_batch(self, batch: MessageBatch) -> None:
        with _network_lock:
            peer = _network.get(self.target)
        if peer is None or peer._closed:
            raise ConnectionError(f"no transport at {self.target}")
        inj = self.owner.fault_injector
        if inj is None:
            peer.deliver(batch)
            return
        src = self.owner.fault_source or self.owner.address
        for b in inj.on_wire(src, self.target, batch):
            peer.deliver(b)


class _InProcSnapshotConnection(ISnapshotConnection):
    def __init__(self, owner: "InProcTransport", target: str):
        self.owner = owner
        self.target = target

    def close(self) -> None:
        pass

    def query_resume(self, probe: Chunk) -> int:
        """Resume-cursor query (ChunkSink.resume_cursor on the peer):
        direct call — the in-proc analogue of the TCP resume frames."""
        with _network_lock:
            peer = _network.get(self.target)
        if peer is None or peer._closed or peer.resume_handler is None:
            return 0
        return peer.resume_handler(probe)

    def send_chunk(self, chunk: Chunk) -> None:
        with _network_lock:
            peer = _network.get(self.target)
        if peer is None or peer._closed:
            raise ConnectionError(f"no transport at {self.target}")
        inj = self.owner.fault_injector
        if inj is None:
            chunks = (chunk,)
        else:
            src = self.owner.fault_source or self.owner.address
            chunks = inj.on_wire(src, self.target, chunk)
        for c in chunks:
            if not peer.deliver_chunk(c):
                raise ConnectionError(f"chunk rejected by {self.target}")
        if not chunks:
            # chunks ride a RELIABLE stream: a swallowed chunk must fail
            # the send (a real network stalls/breaks the stream) — a
            # silent success here would wedge the sender's raft peer in
            # SNAPSHOT state forever, since the receiver's reassembly
            # never completes and no status is ever reported
            raise ConnectionError("nemesis: snapshot chunk lost")


class InProcTransport(ITransport):
    def __init__(
        self,
        address: str,
        message_handler: MessageHandler,
        chunk_handler: Optional[ChunkHandler] = None,
    ):
        self.address = address
        self.message_handler = message_handler
        self.chunk_handler = chunk_handler
        self._closed = False
        # the unified fault plane (faults.FaultController.on_wire)
        self.fault_injector = None
        # resume-cursor query target (ChunkSink.resume_cursor); set by
        # the NodeHost beside chunk_handler
        self.resume_handler = None

    def name(self) -> str:
        return "inproc"

    def start(self) -> None:
        with _network_lock:
            _network[self.address] = self

    def close(self) -> None:
        self._closed = True
        with _network_lock:
            if _network.get(self.address) is self:
                del _network[self.address]

    def get_connection(self, target: str) -> IConnection:
        return _InProcConnection(self, target)

    def get_snapshot_connection(self, target: str) -> ISnapshotConnection:
        return _InProcSnapshotConnection(self, target)

    def deliver(self, batch: MessageBatch) -> None:
        if not self._closed:
            self.message_handler(batch)

    def deliver_chunk(self, chunk: Chunk) -> bool:
        if self._closed or self.chunk_handler is None:
            return False
        return self.chunk_handler(chunk)
