"""In-process loopback transport.

reference: the chan-based test transports in internal/transport [U].
Multiple NodeHosts in one process register by address in a module-level
network table; delivery is a direct call into the receiver's handler
(which only enqueues — cheap and deadlock-free).  Supports fault
injection (drop/partition hooks) for chaos tests.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..pb import Chunk, MessageBatch
from ..raftio import (
    ChunkHandler,
    IConnection,
    ISnapshotConnection,
    ITransport,
    MessageHandler,
)

_network_lock = threading.Lock()
_network: Dict[str, "InProcTransport"] = {}


def reset_inproc_network() -> None:
    with _network_lock:
        _network.clear()


class _InProcConnection(IConnection):
    def __init__(self, owner: "InProcTransport", target: str):
        self.owner = owner
        self.target = target

    def close(self) -> None:
        pass

    def send_message_batch(self, batch: MessageBatch) -> None:
        with _network_lock:
            peer = _network.get(self.target)
        if peer is None or peer._closed:
            raise ConnectionError(f"no transport at {self.target}")
        if self.owner.drop_hook and self.owner.drop_hook(self.target, batch):
            return  # chaos: silently dropped
        peer.deliver(batch)


class _InProcSnapshotConnection(ISnapshotConnection):
    def __init__(self, owner: "InProcTransport", target: str):
        self.owner = owner
        self.target = target

    def close(self) -> None:
        pass

    def send_chunk(self, chunk: Chunk) -> None:
        with _network_lock:
            peer = _network.get(self.target)
        if peer is None or peer._closed:
            raise ConnectionError(f"no transport at {self.target}")
        if self.owner.drop_hook and self.owner.drop_hook(self.target, chunk):
            return
        if not peer.deliver_chunk(chunk):
            raise ConnectionError(f"chunk rejected by {self.target}")


class InProcTransport(ITransport):
    def __init__(
        self,
        address: str,
        message_handler: MessageHandler,
        chunk_handler: Optional[ChunkHandler] = None,
    ):
        self.address = address
        self.message_handler = message_handler
        self.chunk_handler = chunk_handler
        self._closed = False
        # chaos-injection hook: (target, batch_or_chunk) -> drop?
        self.drop_hook: Optional[Callable] = None

    def name(self) -> str:
        return "inproc"

    def start(self) -> None:
        with _network_lock:
            _network[self.address] = self

    def close(self) -> None:
        self._closed = True
        with _network_lock:
            if _network.get(self.address) is self:
                del _network[self.address]

    def get_connection(self, target: str) -> IConnection:
        return _InProcConnection(self, target)

    def get_snapshot_connection(self, target: str) -> ISnapshotConnection:
        return _InProcSnapshotConnection(self, target)

    def deliver(self, batch: MessageBatch) -> None:
        if not self._closed:
            self.message_handler(batch)

    def deliver_chunk(self, chunk: Chunk) -> bool:
        if self._closed or self.chunk_handler is None:
            return False
        return self.chunk_handler(chunk)
