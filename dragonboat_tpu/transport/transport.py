"""Transport core: per-target async send queues, batch coalescing and a
circuit breaker, over any pluggable ITransport.

reference: internal/transport/transport.go (+ job.go) [U].  The raft step
path calls ``send(msg)`` which never blocks: messages go to a bounded
per-target queue drained by a sender thread that coalesces them into one
``MessageBatch`` per wakeup.  Send failures trip a per-target breaker and
surface as ReportUnreachableNode so leaders back off (reference: circuit
breaker util [U]).
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from .. import settings
from ..bigstate.pacing import TokenBucket
from ..logger import get_logger
from ..pb import Message, MessageBatch, MessageType
from ..raftio import ITransport

_log = get_logger("transport")

# breaker states (exported as a gauge value: 0=closed 1=half-open 2=open)
_CLOSED, _HALF_OPEN, _OPEN = 0, 1, 2
_STATE_NAMES = {_CLOSED: "closed", _HALF_OPEN: "half-open", _OPEN: "open"}


class _Breaker:
    """Circuit breaker with exponential backoff and half-open probing.

    Opens after ``threshold`` consecutive failures.  After a jittered
    cooldown, ``ready()`` admits exactly ONE probe batch (half-open); a
    probe success closes the breaker and resets the cooldown, a probe
    failure reopens it with the cooldown doubled (capped) — so a peer
    that stays dead costs geometrically fewer connection attempts,
    while a healed peer is rediscovered within one cooldown.  The
    jitter desynchronizes many senders probing one recovered peer.

    Single-threaded per target (only its sender thread touches it);
    the metrics accessors read plain ints/floats, safe under the GIL.
    """

    def __init__(
        self,
        threshold: int = 3,
        cooldown: float = 0.5,
        max_cooldown: float = 10.0,
        jitter: float = 0.25,
        rng: Optional[random.Random] = None,
    ):
        self.threshold = threshold
        self.base_cooldown = cooldown
        self.max_cooldown = max_cooldown
        self.jitter = jitter
        self._rng = rng or random.Random()
        self.failures = 0
        self.state = _CLOSED
        self.opened_at = 0.0
        self.cooldown = cooldown
        self._wait = cooldown  # jittered effective cooldown
        # per-target observability (surfaced through metrics.py)
        self.open_count = 0
        self._open_seconds = 0.0  # completed open/half-open intervals

    def ready(self) -> bool:
        if self.state == _CLOSED:
            return True
        if self.state == _OPEN:
            if time.monotonic() - self.opened_at >= self._wait:
                self.state = _HALF_OPEN
                return True  # the one probe
            return False
        return False  # half-open: probe already in flight

    def success(self) -> None:
        if self.state != _CLOSED:
            self._open_seconds += time.monotonic() - self.opened_at
        self.state = _CLOSED
        self.failures = 0
        self.cooldown = self.base_cooldown

    def failure(self) -> None:
        self.failures += 1
        if self.state == _HALF_OPEN:
            # probe failed: back off exponentially
            self.cooldown = min(self.cooldown * 2.0, self.max_cooldown)
            self._reopen(accumulate=True)
        elif self.state == _CLOSED and self.failures >= self.threshold:
            self.cooldown = self.base_cooldown
            self._reopen(accumulate=False)

    def _reopen(self, accumulate: bool) -> None:
        now = time.monotonic()
        if accumulate:
            self._open_seconds += now - self.opened_at
        self.state = _OPEN
        self.open_count += 1
        self.opened_at = now
        self._wait = self.cooldown * (
            1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        )

    # -- metrics ----------------------------------------------------------
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    def open_seconds(self) -> float:
        """Total time spent open/half-open, including a current stint."""
        if self.state == _CLOSED:
            return self._open_seconds
        return self._open_seconds + (time.monotonic() - self.opened_at)


class _SendQueue:
    def __init__(self, maxlen: int):
        self.q: deque = deque()
        self.maxlen = maxlen
        self.cond = threading.Condition()
        self.closed = False


class Transport:
    """The messaging service shared by all shards of a NodeHost."""

    def __init__(
        self,
        raw: ITransport,
        resolver: Callable[[int, int], Optional[str]],
        source_address: str,
        deployment_id: int = 0,
        unreachable_cb: Optional[Callable[[Message], None]] = None,
        snapshot_source_opener: Optional[Callable[[object], object]] = None,
        snapshot_status_cb: Optional[Callable[[int, int, bool], None]] = None,
        max_snapshot_send_bytes_per_second: int = 0,
        metrics_registry=None,
        stream_event_cb: Optional[Callable[[int, str, str], None]] = None,
    ):
        self.raw = raw
        self.resolver = resolver
        self.source_address = source_address
        self.deployment_id = deployment_id
        self.unreachable_cb = unreachable_cb
        # opens a leased incremental reader over the snapshot dir; ALL
        # payload reads happen on the stream-job thread, never on the
        # step worker (storage/snapshotter.SnapshotSource)
        self.snapshot_source_opener = snapshot_source_opener
        # (shard_id, to_replica, failed) -> report to the sending raft peer
        self.snapshot_status_cb = snapshot_status_cb
        self.max_snapshot_send_rate = max_snapshot_send_bytes_per_second
        # ONE bucket shared by every stream job: the cap bounds this
        # host's aggregate snapshot egress, not each stream's (N
        # concurrent catch-ups used to multiply the cap N-fold).  The
        # bucket is live-retunable (set_snapshot_send_rate / the
        # bigstate.pacing.CapFeedback loop).
        self.snapshot_pacer: Optional[TokenBucket] = (
            TokenBucket(max_snapshot_send_bytes_per_second)
            if max_snapshot_send_bytes_per_second > 0
            else None
        )
        # throttle seconds of DISCARDED buckets: the *_total metric must
        # stay monotone across cap off->on transitions (a counter that
        # resets breaks every rate()/delta consumer)
        self._stream_throttled_base = 0.0
        # (shard_id, kind, detail) -> flight-recorder lane (nodehost)
        self.stream_event_cb = stream_event_cb
        self._stream_jobs = 0
        self._stream_lock = threading.Lock()
        self._queues: Dict[str, _SendQueue] = {}
        self._breakers: Dict[str, _Breaker] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._stopped = False
        self.metrics = {
            "sent": 0, "dropped": 0, "failed": 0, "snapshots_sent": 0,
            # the snapshot_stream_* surface (docs/BIGSTATE.md): chunk and
            # byte egress, resume events (a retry that continued from a
            # non-zero receiver cursor instead of restarting), and the
            # cumulative seconds the bandwidth cap held senders back
            "stream_chunks": 0, "stream_bytes": 0, "stream_resumes": 0,
        }
        self._metrics_registry = metrics_registry
        # the unified fault plane (faults.FaultController); propagated
        # to the raw ITransport so every outbound batch/chunk crosses it
        self.fault_injector = None

    def set_fault_injector(self, injector) -> None:
        self.fault_injector = injector
        self.raw.fault_injector = injector
        # fault plans target raft addresses; make the raw transport
        # report that identity (not its bind address) to on_wire
        self.raw.fault_source = self.source_address

    def start(self) -> None:
        self.raw.start()

    def close(self) -> None:
        self._stopped = True
        with self._lock:
            queues = list(self._queues.values())
        for sq in queues:
            with sq.cond:
                sq.closed = True
                sq.cond.notify_all()
        for t in list(self._threads.values()):
            t.join(timeout=2.0)
        self.raw.close()

    # -- send path --------------------------------------------------------
    def send(self, m: Message) -> bool:
        """Non-blocking enqueue; False if the message was dropped."""
        if self._stopped:
            return False
        if m.type == MessageType.INSTALL_SNAPSHOT:
            return self.send_snapshot(m)
        target = self.resolver(m.shard_id, m.to)
        if target is None:
            self.metrics["dropped"] += 1
            return False
        sq = self._get_queue(target)
        with sq.cond:
            if sq.closed or len(sq.q) >= sq.maxlen:
                self.metrics["dropped"] += 1
                full = not sq.closed
            else:
                sq.q.append(m)
                sq.cond.notify()
                return True
        if full:
            # a full queue means the peer isn't draining: report it
            # unreachable so the leader backs off (silently dropping
            # here left congested peers hammered at full rate)
            self._notify_unreachable([m])
        return False

    def _get_queue(self, target: str) -> _SendQueue:
        with self._lock:
            sq = self._queues.get(target)
            if sq is None:
                sq = _SendQueue(settings.Soft.send_queue_length)
                self._queues[target] = sq
                self._breakers[target] = b = _Breaker()
                self._register_breaker_metrics(target, b)
                t = threading.Thread(
                    target=self._sender_main,
                    args=(target, sq),
                    daemon=True,
                    name=f"tpu-raft-send-{target}",
                )
                self._threads[target] = t
                t.start()
            return sq

    def _register_breaker_metrics(self, target: str, b: _Breaker) -> None:
        """Per-target breaker observability: state, open transitions and
        cumulative time-in-open, labelled by target (chaos runs watch
        these to see breaker flaps)."""
        reg = self._metrics_registry
        if reg is None:
            return
        labels = {"target": target}
        reg.gauge(
            "raft_transport_breaker_state", lambda b=b: b.state, labels=labels
        )
        reg.gauge(
            "raft_transport_breaker_opens_total",
            lambda b=b: b.open_count,
            labels=labels,
        )
        reg.gauge(
            "raft_transport_breaker_open_seconds_total",
            lambda b=b: b.open_seconds(),
            labels=labels,
        )

    def breaker_stats(self) -> Dict[str, Dict]:
        """Snapshot of every per-target breaker (tests + debugging)."""
        with self._lock:
            breakers = dict(self._breakers)
        return {
            t: {
                "state": b.state_name(),
                "failures": b.failures,
                "open_count": b.open_count,
                "open_seconds": b.open_seconds(),
                "cooldown": b.cooldown,
            }
            for t, b in breakers.items()
        }

    def _sender_main(self, target: str, sq: _SendQueue) -> None:
        breaker = self._breakers[target]
        conn = None
        while True:
            with sq.cond:
                while not sq.q and not sq.closed:
                    sq.cond.wait(timeout=1.0)
                    if self._stopped:
                        return
                if sq.closed and not sq.q:
                    return
                msgs = list(sq.q)
                sq.q.clear()
            if not breaker.ready():
                self.metrics["dropped"] += len(msgs)
                self._notify_unreachable(msgs)
                continue
            batch = MessageBatch(
                messages=tuple(msgs),
                source_address=self.source_address,
                deployment_id=self.deployment_id,
            )
            try:
                if conn is None:
                    conn = self.raw.get_connection(target)
                conn.send_message_batch(batch)
                breaker.success()
                self.metrics["sent"] += len(msgs)
            except Exception as e:  # noqa: BLE001 — any transport error
                _log.debug("send to %s failed: %s", target, e)
                breaker.failure()
                self.metrics["failed"] += len(msgs)
                conn = None
                self._notify_unreachable(msgs)

    # -- snapshot lane ----------------------------------------------------
    def send_snapshot(self, m: Message) -> bool:
        """Stream a snapshot to the target over the chunk lane
        (reference: Transport.SendSnapshot -> stream job [U]).

        NOTHING is read on the calling step worker: the job thread opens
        a ``SnapshotSource`` (which takes a storage GC lease) and reads
        the container incrementally, one chunk in memory at a time — a
        snapshot far larger than RAM streams fine and the step worker's
        stall is bounded by a thread spawn (reference: job.go incremental
        chunk reads [U]).
        """
        if self._stopped:
            return False
        target = self.resolver(m.shard_id, m.to)
        if target is None:
            self._snapshot_failed(m)
            return False
        with self._stream_lock:
            if self._stream_jobs >= settings.Soft.max_concurrent_streaming_snapshots:
                self._snapshot_failed(m)
                return False
            self._stream_jobs += 1
        t = threading.Thread(
            target=self._stream_job,
            args=(m, target),
            daemon=True,
            name=f"tpu-raft-snapshot-{target}",
        )
        t.start()
        return True

    def set_snapshot_send_rate(self, rate: int) -> None:
        """Retune the shared stream cap at runtime (the CapFeedback
        loop's knob; 0/negative removes the cap).  In-flight streams
        pick the new rate up at their next chunk."""
        self.max_snapshot_send_rate = rate
        if rate > 0:
            if self.snapshot_pacer is None:
                self.snapshot_pacer = TokenBucket(rate)
            else:
                self.snapshot_pacer.set_rate(rate)
        elif self.snapshot_pacer is not None:
            # keep the *_total throttle counter monotone past the
            # bucket's retirement
            self._stream_throttled_base += self.snapshot_pacer.throttled_seconds
            self.snapshot_pacer = None

    def active_stream_jobs(self) -> int:
        """Snapshot stream jobs currently in flight (the
        snapshot_stream_active gauge source; public accessor so
        consumers like the balance executor's catchup progress report
        don't reach into the private counter)."""
        return self._stream_jobs

    def stream_throttled_seconds(self) -> float:
        """Cumulative cap-induced sleep across ALL buckets this
        transport ever ran (the snapshot_stream_throttle_seconds_total
        gauge source — monotone even when the cap is toggled)."""
        p = self.snapshot_pacer
        return self._stream_throttled_base + (
            p.throttled_seconds if p is not None else 0.0
        )

    def _stream_event(self, shard_id: int, kind: str, detail: str) -> None:
        cb = self.stream_event_cb
        if cb is None:
            return
        try:
            cb(shard_id, kind, detail)
        except Exception:  # noqa: BLE001 — observability must not
            # break the stream job
            _log.exception("stream event callback raised")

    def _stream_job(self, m: Message, target: str) -> None:
        """One stream job with BOUNDED retry: a transient failure (peer
        restarting, a fault window, one torn connection) RESUMES after a
        short backoff instead of immediately reporting the snapshot
        failed — reporting failure resets the remote to WAIT and costs a
        full leader round trip before the next attempt.  Each retry asks
        the receiver for its receive cursor (``query_resume``) and
        continues from there; chunks already on the receiver's disk are
        neither read nor re-sent.  Only after
        ``snapshot_stream_max_tries`` consecutive failures is the
        failure surfaced (snapshot_status_cb + unreachable)."""
        source = None
        tries = max(1, settings.Soft.snapshot_stream_max_tries)
        self._stream_event(
            m.shard_id, "snapshot_stream_start",
            f"to={m.to} index={m.snapshot.index} target={target}",
        )
        try:
            if not m.snapshot.dummy and self.snapshot_source_opener is not None:
                source = self.snapshot_source_opener(m.snapshot)
            for attempt in range(tries):
                try:
                    self._stream_once(m, target, source, attempt)
                    self.metrics["snapshots_sent"] += 1
                    self._stream_event(
                        m.shard_id, "snapshot_stream_complete",
                        f"to={m.to} index={m.snapshot.index}",
                    )
                    return
                except Exception as e:  # noqa: BLE001 — any transport error
                    if self._stopped or attempt == tries - 1:
                        raise
                    _log.warning(
                        "snapshot stream to %s failed (attempt %d/%d): %s",
                        target, attempt + 1, tries, e,
                    )
                    # sliced backoff so close() interrupts promptly
                    wait = 0.05 * (2 ** attempt)
                    deadline = time.monotonic() + wait
                    while not self._stopped and time.monotonic() < deadline:
                        time.sleep(0.02)
                    if self._stopped:
                        raise
        except Exception as e:  # noqa: BLE001 — retries exhausted
            _log.warning("snapshot stream to %s failed: %s", target, e)
            self._stream_event(
                m.shard_id, "snapshot_stream_fail",
                f"to={m.to} index={m.snapshot.index}: {e}",
            )
            self._snapshot_failed(m)
            if self.unreachable_cb is not None:
                self.unreachable_cb(m)
        finally:
            if source is not None:
                source.close()  # releases the storage GC lease
            with self._stream_lock:
                self._stream_jobs -= 1

    def _stream_once(
        self, m: Message, target: str, source, attempt: int = 0
    ) -> None:
        from .chunk import iter_snapshot_chunks, resume_probe

        start = 0
        if attempt > 0 and source is not None and not m.snapshot.dummy:
            # a RETRY of a partially-delivered stream: ask the receiver
            # where its cursor stands.  The query rides its OWN probe
            # connection: an old receiver closes the socket on the
            # unknown frame kind, and chunks sent down that same dead
            # socket would burn the whole attempt — a fresh chunk
            # connection below keeps restart-from-zero working against
            # pre-resume peers.  Any query failure answers 0, which the
            # receiver's idempotent re-delivery tolerates.
            probe_conn = self.raw.get_snapshot_connection(target)
            try:
                start = probe_conn.query_resume(resume_probe(m, source))
            except Exception:  # noqa: BLE001 — degrade to restart
                start = 0
            finally:
                probe_conn.close()
        conn = self.raw.get_snapshot_connection(target)
        sent_chunks = 0
        sent_bytes = 0
        try:
            if start > 0:
                with self._stream_lock:
                    self.metrics["stream_resumes"] += 1
                self._stream_event(
                    m.shard_id, "snapshot_stream_resume",
                    f"to={m.to} index={m.snapshot.index} "
                    f"from_chunk={start}",
                )
            inj = self.fault_injector
            # the nemesis stream plane (faults.STREAM_KINDS); getattr so
            # bespoke test injectors with only on_wire keep working
            stream_hook = getattr(inj, "on_snapshot_stream", None)
            for c in iter_snapshot_chunks(m, source, start_chunk=start):
                if self._stopped:
                    raise ConnectionError("transport stopped")
                if stream_hook is not None:
                    # snapshot_stream_kill raises here — the streamer
                    # dies mid-transfer and the retry/resume path above
                    # picks the transfer back up
                    stream_hook(self.source_address, target, c)
                conn.send_chunk(c)
                sent_chunks += 1
                sent_bytes += len(c.data)
                # re-read per chunk: set_snapshot_send_rate promises
                # in-flight streams pick a NEW/removed cap up at their
                # next chunk, not just a retuned existing bucket
                pacer = self.snapshot_pacer
                if pacer is not None:
                    # token-bucket cap shared across ALL stream jobs:
                    # follower catch-up cannot starve the commit path
                    # of bandwidth (bigstate.pacing; the cumulative
                    # sleep surfaces as snapshot_stream_throttle_*)
                    pacer.throttle(
                        len(c.data), should_abort=lambda: self._stopped
                    )
        finally:
            conn.close()
            with self._stream_lock:
                self.metrics["stream_chunks"] += sent_chunks
                self.metrics["stream_bytes"] += sent_bytes

    def _snapshot_failed(self, m: Message) -> None:
        if self.snapshot_status_cb is not None:
            self.snapshot_status_cb(m.shard_id, m.to, True)

    def _notify_unreachable(self, msgs) -> None:
        if self.unreachable_cb is None:
            return
        seen = set()
        for m in msgs:
            key = (m.shard_id, m.to)
            if key not in seen:
                seen.add(key)
                self.unreachable_cb(m)
