"""Concurrent history recording + the instrumented audit client.

An :class:`Op` is one client-visible operation with its real-time
interval ``[invoke, ret]`` (``time.monotonic`` instants) and a final
status:

* ``ok``   — the cluster acked it; it definitely took effect (writes)
  or definitely observed the returned value (reads);
* ``fail`` — it definitely did NOT take effect (rejected before
  proposal, or a read that never returned — reads have no effect);
* ``ambig`` — *maybe committed*: the client gave up on a timeout (or a
  terminated/closed replica after the entry may already have been
  replicated).  Ambiguous writes keep ``ret = +inf`` — their effect may
  surface at ANY later point, which is exactly how the checker treats
  them (free to linearize anywhere after invoke, or never).

:class:`AuditClient` drives ``Session``-based ``sync_propose`` /
``sync_read`` / ``stale_read`` against a *live host map* (hosts churn
under the nemesis, so every attempt re-picks a live NodeHost).  Write
retries keep the SAME series id, so the server's session registry
dedupes re-applies — the exactly-once property the session pass then
proves from the replica journals.
"""
from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import asdict, dataclass
from random import Random
from typing import Dict, List, Optional

from ..nodehost import (
    NodeHostClosed,
    RequestRejected,
    RequestTerminated,
    TimeoutError_,
)
from .model import audit_set_cmd

# errors after which the entry MAY already be replicated (ambiguous);
# isinstance, not type-name matching — a subclassed timeout must never
# demote "maybe committed" to "definitely failed" (that would make the
# audit unsound, not conservative)
_MAYBE_COMMITTED_ERRORS = (TimeoutError_, RequestTerminated, NodeHostClosed)


@dataclass
class Op:
    client: int
    index: int
    # "w" | "r" | "stale" | "bounded".  Follower-linearizable reads
    # record as "r": they promise the SAME contract as a leader read,
    # so they join the Wing–Gong pass unchanged — that IS the safety
    # check (docs/READPLANE.md).  "bounded" reads are exempt from
    # recency but carry their stamp in ``value`` as (applied_index,
    # staleness_ticks, bound_ticks) for check_bounded_reads.
    kind: str
    key: object
    value: object = None  # written value (writes) / stamp (bounded reads)
    output: object = None  # observed value (reads) / apply index (writes)
    status: str = "pending"  # pending -> ok | fail | ambig
    invoke: float = 0.0
    ret: float = math.inf

    def describe(self) -> str:
        iv = f"{self.invoke:.6f}"
        rv = "inf" if self.ret == math.inf else f"{self.ret:.6f}"
        return (
            f"c{self.client}#{self.index} {self.kind}({self.key!r}"
            f"{'=' + repr(self.value) if self.kind == 'w' else ''})"
            f" -> {self.status}"
            f"{':' + repr(self.output) if self.kind != 'w' else ''}"
            f" [{iv}, {rv}]"
        )


class HistoryRecorder:
    """Thread-safe append-only op log shared by all audit clients."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ops: List[Op] = []
        self._clients = 0

    def new_client(self) -> int:
        with self._lock:
            self._clients += 1
            return self._clients

    def invoke(self, client: int, kind: str, key, value=None) -> Op:
        op = Op(
            client=client,
            index=0,
            kind=kind,
            key=key,
            value=value,
            invoke=time.monotonic(),
        )
        with self._lock:
            op.index = len(self._ops)
            self._ops.append(op)
        return op

    def ok(self, op: Op, output=None) -> None:
        op.ret = time.monotonic()
        op.output = output
        op.status = "ok"

    def fail(self, op: Op) -> None:
        op.ret = time.monotonic()
        op.status = "fail"

    def ambiguous(self, op: Op) -> None:
        # ret stays +inf: a maybe-committed effect can land any time later
        op.status = "ambig"

    def ops(self) -> List[Op]:
        with self._lock:
            return list(self._ops)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for o in self.ops():
            out[o.status] = out.get(o.status, 0) + 1
        return out

    def ops_for(self, key_prefix) -> List[Op]:
        """The sub-history whose keys start with ``key_prefix`` (str or
        bytes, matched against same-typed keys).  A recorder shared by
        several shards keys each shard's traffic under its own prefix;
        the per-key linearizability search never mixes them, but the
        SESSION pass must be scoped to the one shard whose replica
        journals it is judging against — this is that scope."""
        return [
            o for o in self.ops()
            if isinstance(o.key, type(key_prefix))
            and o.key.startswith(key_prefix)
        ]

    # -- replay serialization (docs/AUDIT.md) ----------------------------
    def to_jsonl(self) -> str:
        return "\n".join(
            json.dumps(
                {**asdict(o), "ret": None if o.ret == math.inf else o.ret}
            )
            for o in self.ops()
        )

    @staticmethod
    def ops_from_jsonl(text: str) -> List[Op]:
        out = []
        for line in text.splitlines():
            if not line.strip():
                continue
            d = json.loads(line)
            if d.get("ret") is None:
                d["ret"] = math.inf
            if isinstance(d.get("key"), list):
                # tuple keys serialize as JSON lists; the checker
                # partitions by key, so it must be hashable again
                d["key"] = tuple(d["key"])
            out.append(Op(**d))
        return out


class AuditClient:
    """One logical client process (one recorder pid, one Session).

    ``hosts`` is either a dict ``key -> NodeHost`` or a zero-arg
    callable returning one (the nemesis kills/restarts hosts, so the
    map must be re-read per attempt).  All request errors are folded
    into the three-way ok/fail/ambig verdict the checker understands.
    """

    def __init__(
        self,
        hosts,
        shard_id: int,
        recorder: HistoryRecorder,
        *,
        seed: int = 0,
        budget=None,
        op_timeout: float = 8.0,
        per_try_timeout: float = 1.0,
    ):
        self._hosts = hosts
        self.shard_id = shard_id
        self.recorder = recorder
        self.client = recorder.new_client()
        self.budget = budget
        self.op_timeout = op_timeout
        self.per_try_timeout = per_try_timeout
        self._rng = Random((seed << 8) ^ self.client)
        self.session = None
        self._seq = 0
        self.stats: Dict[str, int] = {}

    # -- host selection ---------------------------------------------------
    def _live_hosts(self) -> list:
        d = self._hosts() if callable(self._hosts) else self._hosts
        # the nemesis kills/restarts hosts from its own thread, so the
        # map can resize mid-iteration — retry the snapshot instead of
        # letting RuntimeError kill the workload thread
        for _ in range(8):
            try:
                items = sorted(d.items(), key=lambda kv: str(kv[0]))
                break
            except RuntimeError:
                continue
        else:
            return []
        return [
            nh for _, nh in items if not getattr(nh, "_closed", False)
        ]

    def _host(self):
        live = self._live_hosts()
        return self._rng.choice(live) if live else None

    def _count(self, k: str) -> None:
        self.stats[k] = self.stats.get(k, 0) + 1

    def _deadline(self) -> float:
        budget = (
            self.budget.total_timeout() if self.budget is not None
            else self.op_timeout
        )
        return time.monotonic() + budget

    def _per_try(self, deadline: float) -> float:
        per = (
            self.budget.per_try_timeout() if self.budget is not None
            else self.per_try_timeout
        )
        return max(0.05, min(per, deadline - time.monotonic()))

    # -- session lifecycle ------------------------------------------------
    def register(self, deadline: Optional[float] = None) -> bool:
        """(Re-)register the exactly-once session through any live host."""
        deadline = deadline or self._deadline()
        while time.monotonic() < deadline:
            nh = self._host()
            if nh is None:
                time.sleep(0.05)
                continue
            try:
                self.session = nh.sync_get_session(
                    self.shard_id, timeout=self._per_try(deadline)
                )
                return True
            except Exception:  # noqa: BLE001 — any failure: try another host
                self._count("register_retries")
                time.sleep(0.02)
        return False

    # -- operations -------------------------------------------------------
    def write(self, key):
        """One exactly-once write of a globally-unique value.  Returns
        the value written (regardless of verdict — the checker reads
        the verdict from the history)."""
        self._seq += 1
        value = f"c{self.client}-{self._seq}"
        op = self.recorder.invoke(self.client, "w", key, value)
        deadline = self._deadline()
        if self.session is None and not self.register(deadline):
            self.recorder.fail(op)  # never proposed
            self._count("no_session")
            return value
        cmd = audit_set_cmd(key, value)
        maybe_committed = False
        while True:
            nh = self._host()
            if self.session is None:
                # evicted/rejected mid-run: re-register before retrying
                # (a dead session would burn the whole deadline raising)
                if not self.register(deadline):
                    break
                continue
            if nh is None:
                time.sleep(0.05)
            else:
                try:
                    t_try = time.monotonic()
                    r = nh.sync_propose(
                        self.session, cmd, timeout=self._per_try(deadline)
                    )
                    self.session.proposal_completed()
                    self.recorder.ok(op, getattr(r, "value", None))
                    if self.budget is not None:
                        # the SUCCESSFUL attempt's latency only: whole-
                        # loop time includes backoff/election waits and
                        # would ratchet the budget upward
                        self.budget.observe(time.monotonic() - t_try)
                    return value
                except Exception as e:  # noqa: BLE001 — classified below
                    self._count(f"write_{type(e).__name__}")
                    if isinstance(e, _MAYBE_COMMITTED_ERRORS):
                        # the entry may already be in the log
                        maybe_committed = True
                    elif isinstance(e, RequestRejected):
                        # session evicted / series marked responded —
                        # this copy was NOT applied; an earlier timed-out
                        # copy may have been, so ambiguity persists
                        self.session = None
                        if maybe_committed:
                            # do NOT re-propose under a fresh session: a
                            # maybe-committed earlier copy has no dedupe
                            # state there, and a second apply would be a
                            # real duplicate — finalize as ambiguous
                            break
                    time.sleep(0.02)
            if time.monotonic() >= deadline:
                break
        if maybe_committed:
            self.recorder.ambiguous(op)
            # burn the series: a later retry of it could double-apply
            # only through the session registry, which dedupes — but the
            # NEXT op must ride a fresh series either way
            if self.session is not None:
                self.session.proposal_completed()
        else:
            self.recorder.fail(op)
        return value

    def read(self, key):
        """Linearizable read (read-index).  A read that never returns
        constrains nothing — recorded as fail and excluded."""
        op = self.recorder.invoke(self.client, "r", key)
        deadline = self._deadline()
        while time.monotonic() < deadline:
            nh = self._host()
            if nh is None:
                time.sleep(0.05)
                continue
            try:
                v = nh.sync_read(
                    self.shard_id, ("get", key),
                    timeout=self._per_try(deadline),
                )
                self.recorder.ok(op, v)
                return v
            except Exception as e:  # noqa: BLE001 — reads are idempotent
                self._count(f"read_{type(e).__name__}")
                time.sleep(0.02)
        self.recorder.fail(op)
        return None

    def stale_read(self, key):
        """Local (non-linearizable) read: checked only against the
        weaker never-saw-an-uncommitted-value contract."""
        op = self.recorder.invoke(self.client, "stale", key)
        nh = self._host()
        if nh is None:
            self.recorder.fail(op)
            return None
        try:
            v = nh.stale_read(self.shard_id, ("get", key))
            self.recorder.ok(op, v)
            return v
        except Exception as e:  # noqa: BLE001
            self._count(f"stale_{type(e).__name__}")
            self.recorder.fail(op)
            return None

    def follower_read(self, key):
        """Follower-linearizable read (docs/READPLANE.md): served from
        any replica's local state machine after its ReadIndex round.
        Recorded as kind "r" — it promises exactly the leader read's
        contract, so the Wing–Gong pass judges it unchanged (that IS
        the follower-read safety check)."""
        op = self.recorder.invoke(self.client, "r", key)
        deadline = self._deadline()
        while time.monotonic() < deadline:
            nh = self._host()
            if nh is None:
                time.sleep(0.05)
                continue
            try:
                v, _applied = nh.follower_read(
                    self.shard_id, ("get", key),
                    timeout=self._per_try(deadline),
                )
                self.recorder.ok(op, v)
                return v
            except Exception as e:  # noqa: BLE001 — reads are idempotent
                self._count(f"follower_{type(e).__name__}")
                time.sleep(0.02)
        self.recorder.fail(op)
        return None

    def bounded_read(self, key, bound_ticks: int = 50):
        """Bounded-staleness read: one attempt against one live host
        (like stale_read — retrying elsewhere is the GATEWAY's job; the
        audit records what one replica answered).  The stamp rides
        ``op.value`` as (applied_index, staleness_ticks, bound_ticks)
        for check_bounded_reads; a shed records as fail (no effect)."""
        op = self.recorder.invoke(self.client, "bounded", key)
        nh = self._host()
        if nh is None:
            self.recorder.fail(op)
            return None
        try:
            res = nh.bounded_read(self.shard_id, ("get", key),
                                  bound_ticks=bound_ticks)
            op.value = (res.applied_index, res.staleness_ticks, bound_ticks)
            self.recorder.ok(op, res.value)
            return res.value
        except Exception as e:  # noqa: BLE001 — shed or host closing
            self._count(f"bounded_{type(e).__name__}")
            self.recorder.fail(op)
            return None

    def close(self, timeout: float = 2.0) -> None:
        """Best-effort session unregister (the registry LRU also GCs)."""
        s, self.session = self.session, None
        if s is None:
            return
        nh = self._host()
        if nh is None:
            return
        try:
            nh.sync_close_session(s, timeout=timeout)
        except Exception:  # noqa: BLE001 — the LRU will evict it
            pass


def run_workload(
    clients: List[AuditClient],
    keys: List,
    stop: threading.Event,
    *,
    read_ratio: float = 0.35,
    stale_ratio: float = 0.1,
    follower_ratio: float = 0.0,
    bounded_ratio: float = 0.0,
    bound_ticks: int = 50,
    pace: float = 0.002,
) -> List[threading.Thread]:
    """Spawn one daemon thread per client running a mixed write/read/
    stale-read(/follower/bounded) loop over ``keys`` until ``stop`` is
    set.  Returns the (started) threads; join them after setting
    ``stop``.  The readplane ratios default to 0 so pre-readplane
    workloads keep their exact op mix."""

    def loop(c: AuditClient):
        while not stop.is_set():
            key = c._rng.choice(keys)
            roll = c._rng.random()
            if roll < read_ratio:
                c.read(key)
            elif roll < read_ratio + stale_ratio:
                c.stale_read(key)
            elif roll < read_ratio + stale_ratio + follower_ratio:
                c.follower_read(key)
            elif roll < (read_ratio + stale_ratio + follower_ratio
                         + bounded_ratio):
                c.bounded_read(key, bound_ticks=bound_ticks)
            else:
                c.write(key)
            time.sleep(pace)

    threads = [
        threading.Thread(target=loop, args=(c,), daemon=True,
                         name=f"audit-client-{c.client}")
        for c in clients
    ]
    for t in threads:
        t.start()
    return threads
