"""The audited state machine and its pure replay model.

``AuditKV`` is the kv/register SM an audited cluster runs: a plain KV
store that additionally appends every applied write to an in-memory
**apply journal** ``[(index, key, value), ...]``.  The journal is what
makes the exactly-once session pass white-box checkable: audit clients
write globally-unique values, so a duplicate apply shows up as the same
value twice in a replica's journal and a lost ack as an acked value
missing from it (see :func:`dragonboat_tpu.audit.checker.check_sessions`).
The journal is serialized into snapshots beside the data so a
snapshot-recovered replica's journal stays comparable.

The *replay model* used by the linearizability search is the trivial
per-key register: a write sets the register, a read returns it — it
lives inline in the checker (the search only needs "apply one op to a
register value"), this module just pins the command codec both sides
share.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Tuple

from ..statemachine import IStateMachine, Result


def audit_set_cmd(key, value) -> bytes:
    """The one write-command shape AuditKV applies.  JSON, not pickle:
    commands travel the wire and the library-wide no-pickle guard
    (tests/test_wire_payloads.py) applies to the audit SM too."""
    return json.dumps(["set", key, value]).encode()


class AuditKV(IStateMachine):
    """Journaled KV register store (see module docstring).

    ``lookup`` accepts either a bare key or a ``("get", key)`` tuple so
    the audit client and ad-hoc test probes can share it.
    """

    def __init__(self, shard_id, replica_id):
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.data: Dict = {}
        self.journal: List[Tuple[int, object, object]] = []

    def update(self, entry):
        op, k, v = json.loads(entry.cmd.decode())
        if op != "set":
            raise ValueError(f"AuditKV: unknown op {op!r}")
        if isinstance(k, list):
            # tuple keys JSON-encode as lists; store them hashable again
            # (ops_from_jsonl and recover_from_snapshot do the same)
            k = tuple(k)
        self.data[k] = v
        self.journal.append((entry.index, k, v))
        return Result(value=entry.index)

    def lookup(self, query):
        # tuple OR list: RPC queries ride the JSON value lane, which
        # turns ("get", k) into ["get", k] (transport/wire.py contract)
        if (
            isinstance(query, (tuple, list))
            and len(query) == 2
            and query[0] == "get"
        ):
            query = query[1]
        return self.data.get(query)

    def save_snapshot(self, w, files, done):
        # data ships as a PAIR LIST: JSON object keys stringify, so a
        # dict round-trip would turn integer keys into strings and a
        # snapshot-recovered replica would miss every lookup on them —
        # an audit "violation" that is a harness artifact
        w.write(
            json.dumps([list(self.data.items()), self.journal]).encode()
        )

    def recover_from_snapshot(self, r, files, done):
        pairs, journal = json.loads(r.read().decode())
        self.data = {
            (tuple(k) if isinstance(k, list) else k): v for k, v in pairs
        }
        self.journal = [tuple(e) for e in journal]


def collect_journals(hosts: Dict, shard_id: int) -> Dict[str, list]:
    """Snapshot every live replica's ``(key, value)`` apply journal for
    one shard (white-box, like the chaos suite's agreement check)."""
    out: Dict[str, list] = {}
    for key, nh in hosts.items():
        if getattr(nh, "_closed", False):
            continue
        node = nh._nodes.get(shard_id)
        if node is None:
            continue
        sm = node.sm.managed.sm
        out[str(key)] = [(k, v) for _, k, v in list(sm.journal)]
    return out


def settle_journals(
    hosts: Dict, shard_id: int, timeout: float = 30.0
) -> Dict[str, list]:
    """Wait until every live replica's journal for ``shard_id`` agrees,
    then return the journals.  Raises AssertionError on timeout with
    the divergent sizes (the session pass would only report a less
    specific order mismatch)."""
    deadline = time.monotonic() + timeout
    journals: Dict[str, list] = {}
    while True:
        journals = collect_journals(hosts, shard_id)
        vals = list(journals.values())
        if vals and all(j == vals[0] for j in vals):
            return journals
        if time.monotonic() >= deadline:
            raise AssertionError(
                f"shard {shard_id} journals did not settle within "
                f"{timeout}s: sizes={[len(j) for j in vals]}"
            )
        time.sleep(0.05)
