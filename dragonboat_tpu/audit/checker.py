"""Offline linearizability + exactly-once session checker.

reference: Wing & Gong's simulation search as used by the Knossos /
Porcupine checkers [U].  The search exploits two structural facts of
the audited workload:

* **per-key partitioning** — the model is an independent register per
  key, and linearizability is compositional (Herlihy–Wing locality), so
  each key's sub-history is checked alone;
* **unique write values** — every write carries a globally-unique
  value, so a read pins exactly which write it observed.

For one key the search walks all real-time-respecting linearization
orders: an op may be linearized next iff no other still-pending op
*returned* before it was *invoked*; a write sets the register, a read
must observe it.  Ambiguous (``maybe committed``) writes have
``ret = +inf`` and may be linearized anywhere after their invoke — or
never (success only requires every ``ok`` op to be placed).  Memoizing
on (placed-set, register-value) makes repeated interleavings cheap; a
``bound`` on visited states is the escape hatch for adversarial
histories (the result then says *bounded*, not *ok*).

On violation the failing key's sub-history is shrunk to a 1-minimal
counterexample (greedy delta-debugging: drop any op whose removal keeps
the history non-linearizable) and reported with its real-time window.

Two further passes cover what linearizability alone cannot:

* :func:`check_stale_reads` — ``stale_read`` results are exempt from
  recency but must never surface a value that was *never committed*
  (a definitely-failed write) or one invoked only after the read
  returned;
* :func:`check_sessions` — the exactly-once pass over the replicas'
  apply journals (:class:`dragonboat_tpu.audit.model.AuditKV`):
  replicas agree on apply order, every acked write applied exactly
  once (no lost acks, no duplicate applies), every failed write zero
  times, every ambiguous write at most once.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..logger import get_logger
from .history import Op

_log = get_logger("audit")

DEFAULT_BOUND = 200_000


@dataclass
class Violation:
    key: object
    reason: str
    window: Tuple[float, float]
    ops: List[Op] = field(default_factory=list)

    def describe(self) -> str:
        lines = [
            f"key={self.key!r}: {self.reason} "
            f"(window [{self.window[0]:.6f}, {self.window[1]:.6f}], "
            f"{len(self.ops)} op(s))"
        ]
        lines += [f"  {o.describe()}" for o in self.ops]
        return "\n".join(lines)


@dataclass
class CheckResult:
    ok: bool
    bounded: bool = False
    violations: List[Violation] = field(default_factory=list)
    states: int = 0
    keys_checked: int = 0

    def describe(self) -> str:
        if self.ok:
            extra = " (BOUNDED: some keys not fully searched)" if self.bounded else ""
            return (
                f"linearizable: {self.keys_checked} key(s), "
                f"{self.states} state(s) explored{extra}"
            )
        return "NOT linearizable:\n" + "\n".join(
            v.describe() for v in self.violations
        )


def _window(ops: Sequence[Op]) -> Tuple[float, float]:
    lo = min((o.invoke for o in ops), default=0.0)
    hi = max(
        (o.ret for o in ops if o.ret != math.inf),
        default=max((o.invoke for o in ops), default=0.0),
    )
    return (lo, hi)


def _linearize_key(
    ops: Sequence[Op], initial, bound: int
) -> Tuple[Optional[bool], int]:
    """Search one key's sub-history.  Returns (verdict, states): verdict
    True = linearizable, False = provably not, None = bound exhausted."""
    n = len(ops)
    required = frozenset(i for i in range(n) if ops[i].status == "ok")
    if not required:
        return True, 0
    seen = set()
    states = 0
    stack = [(frozenset(), initial)]
    while stack:
        done, val = stack.pop()
        if (done, val) in seen:
            continue
        seen.add((done, val))
        states += 1
        if states > bound:
            return None, states
        if required <= done:
            return True, states
        pending = [i for i in range(n) if i not in done]
        min_ret = min(ops[i].ret for i in pending)
        for i in pending:
            o = ops[i]
            if o.invoke > min_ret:
                # some still-pending op returned before o was invoked;
                # that op must be linearized first
                continue
            if o.kind == "w":
                stack.append((done | {i}, o.value))
            elif o.output == val:
                stack.append((done | {i}, val))
    return False, states


_MINIMIZE_CAP = 128  # delta-debug is O(n^2) searches; skip huge windows


def _minimize(ops: List[Op], initial, bound: int) -> List[Op]:
    """Greedy 1-minimal shrink of a non-linearizable sub-history."""
    if len(ops) > _MINIMIZE_CAP:
        return ops
    cur = list(ops)
    changed = True
    while changed:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            verdict, _ = _linearize_key(cand, initial, bound)
            if verdict is False:
                cur = cand
                changed = True
                break
    return cur


def check_linearizable(
    ops: Sequence[Op], *, initial=None, bound: int = DEFAULT_BOUND
) -> CheckResult:
    """Per-key Wing–Gong search over a recorded history.

    Participants: ``ok``/``ambig`` writes and ``ok`` linearizable
    reads.  ``fail`` ops definitely had no effect and ``stale``/failed
    reads constrain nothing — both are excluded here (stale reads have
    their own pass)."""
    by_key: Dict[object, List[Op]] = {}
    for o in ops:
        # a still-pending write (workload stopped mid-op) is ambiguous:
        # it may have committed, so it participates with ret=+inf
        if o.kind == "w" and o.status in ("ok", "ambig", "pending"):
            by_key.setdefault(o.key, []).append(o)
        elif o.kind == "r" and o.status == "ok":
            by_key.setdefault(o.key, []).append(o)
    result = CheckResult(ok=True)
    for key in sorted(by_key, key=repr):
        kops = sorted(by_key[key], key=lambda o: (o.invoke, o.ret))
        verdict, states = _linearize_key(kops, initial, bound)
        result.states += states
        result.keys_checked += 1
        if verdict is None:
            result.bounded = True
        elif verdict is False:
            minimal = _minimize(kops, initial, bound)
            result.ok = False
            result.violations.append(
                Violation(
                    key=key,
                    reason="no linearization order exists",
                    window=_window(minimal),
                    ops=minimal,
                )
            )
    return result


def check_stale_reads(ops: Sequence[Op]) -> List[Violation]:
    """The weaker contract stale reads still owe: a returned value must
    be the initial value or some possibly-committed write invoked
    before the read returned — never a definitely-aborted proposal's
    value, never a value from the future."""
    writes = {o.value: o for o in ops if o.kind == "w"}
    out: List[Violation] = []
    for o in ops:
        if o.kind != "stale" or o.status != "ok" or o.output is None:
            continue
        w = writes.get(o.output)
        if w is None:
            out.append(
                Violation(o.key, "stale read observed a never-written value",
                          _window([o]), [o])
            )
        elif w.key != o.key:
            # values are globally unique, so a cross-key hit means the
            # register leaked another key's value
            out.append(
                Violation(o.key,
                          "stale read observed another key's value",
                          _window([w, o]), [w, o])
            )
        elif w.status == "fail":
            out.append(
                Violation(o.key,
                          "stale read observed an aborted proposal's value",
                          _window([w, o]), [w, o])
            )
        elif w.invoke > o.ret:
            out.append(
                Violation(o.key, "stale read observed a future write",
                          _window([w, o]), [w, o])
            )
    return out


def check_bounded_reads(ops: Sequence[Op]) -> List[Violation]:
    """BOUNDED_STALENESS's two promises (docs/READPLANE.md): the
    stamped staleness never exceeds the caller's bound (a read past
    the bound must SHED, not serve), and the value obeys the same
    containment stale reads owe — some possibly-committed write of
    this key invoked before the read returned, never an aborted
    proposal's value, never a value from the future.  The stamp rides
    ``op.value`` as (applied_index, staleness_ticks, bound_ticks)."""
    writes = {o.value: o for o in ops if o.kind == "w"}
    out: List[Violation] = []
    for o in ops:
        if o.kind != "bounded" or o.status != "ok":
            continue
        stamp = o.value
        if not isinstance(stamp, (tuple, list)) or len(stamp) != 3:
            out.append(
                Violation(o.key, "bounded read served without a stamp",
                          _window([o]), [o])
            )
            continue
        _applied, staleness, bound = stamp
        if staleness > bound:
            out.append(
                Violation(
                    o.key,
                    f"bounded read served PAST its bound "
                    f"(staleness {staleness} > bound {bound} ticks)",
                    _window([o]), [o],
                )
            )
        if o.output is None:
            continue
        w = writes.get(o.output)
        if w is None:
            out.append(
                Violation(o.key,
                          "bounded read observed a never-written value",
                          _window([o]), [o])
            )
        elif w.key != o.key:
            out.append(
                Violation(o.key,
                          "bounded read observed another key's value",
                          _window([w, o]), [w, o])
            )
        elif w.status == "fail":
            out.append(
                Violation(o.key,
                          "bounded read observed an aborted proposal's value",
                          _window([w, o]), [w, o])
            )
        elif w.invoke > o.ret:
            out.append(
                Violation(o.key, "bounded read observed a future write",
                          _window([w, o]), [w, o])
            )
    return out


@dataclass
class SessionReport:
    ok: bool
    problems: List[str] = field(default_factory=list)
    acked: int = 0
    applied: int = 0

    def describe(self) -> str:
        if self.ok:
            return (
                f"exactly-once: {self.acked} acked write(s) all applied "
                f"once across {self.applied} journal entr(ies)"
            )
        return "session semantics violated:\n" + "\n".join(
            f"  {p}" for p in self.problems
        )


def check_sessions(
    ops: Sequence[Op], journals: Dict[str, Sequence[tuple]]
) -> SessionReport:
    """The exactly-once pass (see module docstring).  ``journals`` maps
    a replica label to its ``[(key, value), ...]`` apply journal; only
    values present in the recorded history are judged — probe/SLA
    traffic sharing the shard is ignored."""
    report = SessionReport(ok=True)
    if not journals:
        report.ok = False
        report.problems.append("no replica journals to audit")
        return report
    labels = sorted(journals, key=lambda k: len(journals[k]))
    longest = list(journals[labels[-1]])
    report.applied = len(longest)
    for lab in labels[:-1]:
        j = list(journals[lab])
        if longest[: len(j)] != j:
            report.ok = False
            report.problems.append(
                f"replica {lab} journal is not a prefix of "
                f"{labels[-1]}'s (apply-order divergence)"
            )
    counts: Dict[object, int] = {}
    for _, v in longest:
        counts[v] = counts.get(v, 0) + 1
    for o in ops:
        if o.kind != "w":
            continue
        n = counts.get(o.value, 0)
        if o.status == "ok":
            report.acked += 1
            if n == 0:
                report.ok = False
                report.problems.append(
                    f"lost ack: acked write never applied: {o.describe()}"
                )
            elif n > 1:
                report.ok = False
                report.problems.append(
                    f"duplicate apply ({n}x): {o.describe()}"
                )
        elif o.status == "fail" and n > 0:
            report.ok = False
            report.problems.append(
                f"aborted proposal applied ({n}x): {o.describe()}"
            )
        elif o.status in ("ambig", "pending") and n > 1:
            report.ok = False
            report.problems.append(
                f"ambiguous write applied {n}x (exactly-once broken): "
                f"{o.describe()}"
            )
    return report


@dataclass
class AuditReport:
    linearizability: CheckResult
    stale: List[Violation]
    sessions: Optional[SessionReport]
    bounded: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        """The audit gate: passes only if every pass passed AND the
        linearizability search ran to completion — a bound-exhausted
        key was never actually checked, and an audit must not report
        "checked" for it.  Callers that want "no violation found,
        search possibly incomplete" read ``linearizability.ok`` and
        ``linearizability.bounded`` directly."""
        return (
            self.linearizability.ok
            and not self.linearizability.bounded
            and not self.stale
            and not self.bounded
            and (self.sessions is None or self.sessions.ok)
        )

    def describe(self) -> str:
        parts = [self.linearizability.describe()]
        if self.stale:
            parts.append("stale-read violations:")
            parts += [v.describe() for v in self.stale]
        else:
            parts.append("stale reads: ok")
        if self.bounded:
            parts.append("bounded-read violations:")
            parts += [v.describe() for v in self.bounded]
        else:
            parts.append("bounded reads: ok")
        if self.sessions is not None:
            parts.append(self.sessions.describe())
        return "\n".join(parts)


def run_audit(
    ops: Sequence[Op],
    journals: Optional[Dict[str, Sequence[tuple]]] = None,
    *,
    initial=None,
    bound: int = DEFAULT_BOUND,
) -> AuditReport:
    """The full offline audit: linearizability (leader AND follower-
    linearizable reads — both record kind "r") + stale-read pass +
    bounded-read containment + (when journals are given) the
    exactly-once session pass."""
    return AuditReport(
        linearizability=check_linearizable(ops, initial=initial, bound=bound),
        stale=check_stale_reads(ops),
        sessions=None if journals is None else check_sessions(ops, journals),
        bounded=check_bounded_reads(ops),
    )


class AuditGateError(AssertionError):
    """The audit gate failed.  ``timeline`` carries the merged
    flight-recorder/trace timeline of the audited hosts at failure
    time when any of them has observability enabled (obs/,
    docs/OBSERVABILITY.md) — the incident evidence is captured the
    moment the gate trips, not reconstructed afterwards."""

    timeline: str = ""


def assert_audit_ok(report: AuditReport, hosts=(), label: str = "audit"):
    """The audit gate with flight-recorder auto-dump: raise
    :class:`AuditGateError` unless ``report.ok``.  ``hosts`` is the
    audited cluster ({key: NodeHost} dict or iterable of NodeHosts);
    hosts with ``enable_flight_recorder``/``enable_tracing`` contribute
    their rings to the dump attached as ``exc.timeline`` (also logged,
    tail-truncated)."""
    if report.ok:
        return
    exc = AuditGateError(f"{label} gate failed:\n{report.describe()}")
    try:
        from ..obs import attach_timeline
    except Exception:  # noqa: BLE001 — the dump must not mask the verdict
        raise exc from None
    raise attach_timeline(
        exc, hosts, label=f"{label} gate failed", log=_log
    ) from None
