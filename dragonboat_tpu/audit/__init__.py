"""Churn-nemesis linearizability audit harness.

reference: Jepsen's nemesis + offline-checker methodology (Knossos /
Porcupine lineage) and dragonboat's drummer harness [U].  The chaos
suite's invariants (acked writes survive, replicas agree) say a shaken
cluster *recovers*; this package checks the stronger claim — that the
histories clients actually observe while the cluster is being broken
are **linearizable**, and that registered-session retries are
**exactly-once** across ambiguous timeouts.

Three pieces:

* :mod:`.history` — an instrumented client (``AuditClient``) wrapping
  ``Session``-based ``sync_propose`` / ``sync_read`` / ``stale_read``
  that logs invoke/ok/fail/ambiguous events into a concurrent
  ``HistoryRecorder`` (timeouts are *ambiguous*: "maybe committed");
* :mod:`.model` — ``AuditKV``, the journaled kv/register state machine
  the audited cluster runs, plus the pure replay model;
* :mod:`.checker` — the offline checker: per-key Wing–Gong
  linearizability search with a bounded-search escape hatch and a
  minimal failing-window report, a stale-read pass, a bounded-read
  containment pass (readplane: stamped staleness never exceeds the
  bound, docs/READPLANE.md), and the exactly-once session pass over
  replica apply journals.

The churn nemesis itself (scheduled leader kills / transfers /
membership churn / balancer moves) is the ``churn`` plane of
:class:`dragonboat_tpu.faults.FaultController` — see docs/AUDIT.md and
docs/FAULTS.md.
"""
from .checker import (
    AuditGateError,
    AuditReport,
    CheckResult,
    Violation,
    assert_audit_ok,
    check_bounded_reads,
    check_linearizable,
    check_sessions,
    check_stale_reads,
    run_audit,
)
from .history import AuditClient, HistoryRecorder, Op
from .model import AuditKV, audit_set_cmd, collect_journals, settle_journals

__all__ = [
    "AuditClient",
    "AuditGateError",
    "AuditKV",
    "AuditReport",
    "assert_audit_ok",
    "CheckResult",
    "HistoryRecorder",
    "Op",
    "Violation",
    "audit_set_cmd",
    "check_bounded_reads",
    "check_linearizable",
    "check_sessions",
    "check_stale_reads",
    "collect_journals",
    "run_audit",
    "settle_journals",
]
