"""Public state-machine contracts — what users implement.

reference: statemachine/ (statemachine.go, concurrent.go, ondisk.go) [U].
Three tiers, exactly as the reference:

  * ``IStateMachine``           — simple in-memory SM, serialized access.
  * ``IConcurrentStateMachine`` — batched updates + concurrent snapshots.
  * ``IOnDiskStateMachine``     — SM owns its own durable storage; reports
                                  its applied index at ``open`` and only
                                  the log tail is replayed.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import BinaryIO, List, Optional, Tuple


@dataclass
class Result:
    """reference: statemachine.Result [U]."""

    value: int = 0
    data: bytes = b""


@dataclass
class SMEntry:
    """The entry view passed to user Update() (reference:
    statemachine.Entry [U])."""

    index: int = 0
    cmd: bytes = b""
    result: Result = field(default_factory=Result)


@dataclass
class SnapshotFile:
    file_id: int = 0
    filepath: str = ""
    metadata: bytes = b""


class ISnapshotFileCollection(abc.ABC):
    @abc.abstractmethod
    def add_file(self, file_id: int, path: str, metadata: bytes) -> None: ...


class IStateMachine(abc.ABC):
    """Simple in-memory SM (reference: statemachine.IStateMachine [U])."""

    @abc.abstractmethod
    def update(self, entry: SMEntry) -> Result: ...

    @abc.abstractmethod
    def lookup(self, query) -> object: ...

    @abc.abstractmethod
    def save_snapshot(
        self, w: BinaryIO, files: ISnapshotFileCollection, done
    ) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], done
    ) -> None: ...

    def close(self) -> None:
        pass


class IConcurrentStateMachine(abc.ABC):
    """Batched SM with concurrent snapshotting (reference:
    statemachine.IConcurrentStateMachine [U])."""

    @abc.abstractmethod
    def update(self, entries: List[SMEntry]) -> List[SMEntry]: ...

    @abc.abstractmethod
    def lookup(self, query) -> object: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(
        self, ctx, w: BinaryIO, files: ISnapshotFileCollection, done
    ) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(
        self, r: BinaryIO, files: List[SnapshotFile], done
    ) -> None: ...

    def close(self) -> None:
        pass


class IOnDiskStateMachine(abc.ABC):
    """SM that manages its own durable state (reference:
    statemachine.IOnDiskStateMachine [U])."""

    @abc.abstractmethod
    def open(self, stopc) -> int:
        """Open/recover local state; return last applied raft index."""

    @abc.abstractmethod
    def update(self, entries: List[SMEntry]) -> List[SMEntry]: ...

    @abc.abstractmethod
    def lookup(self, query) -> object: ...

    @abc.abstractmethod
    def sync(self) -> None: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(self, ctx, w: BinaryIO, done) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(self, r: BinaryIO, done) -> None: ...

    def close(self) -> None:
        pass


class SnapshotStopped(Exception):
    """Raise from save/recover when ``done`` is set (reference:
    statemachine.ErrSnapshotStopped [U])."""
