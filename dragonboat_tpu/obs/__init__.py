"""Observability: end-to-end request tracing + per-shard flight
recorder (docs/OBSERVABILITY.md).

* :mod:`.trace` — the span model threaded through the proposal/read
  path with trace context carried in wire messages, plus the
  Chrome/Perfetto ``trace_event`` exporter;
* :mod:`.recorder` — the per-shard flight recorder ring buffers,
  dumped on demand (``NodeHost.dump_timeline``) and automatically when
  ``assert_recovery_sla`` trips, an audit gate fails, or the gateway
  sheds sustainedly (``gateway/admission.py``: overload is a state
  transition too — the moment the front door starts refusing work
  there must be a cross-host record of why).

* :mod:`.fleetscope` — the cross-process telemetry plane: the
  ``RPC_OP_OBS`` server side plus the :class:`FleetScope` collector
  merging every fleet process's recorder/span tails into one timeline;
* :mod:`.slo` — declarative objectives evaluated from fleet metric
  deltas into burn-rate rows (``FleetScope.slo_report``).

Both are off by default (``NodeHostConfig.enable_tracing`` /
``enable_flight_recorder``); the disabled hot paths cost one attribute
load.
"""
from .fleetscope import FleetScope, ObsService, ObsUnsupported
from .recorder import (
    FlightRecorder,
    attach_timeline,
    format_timeline,
    hosts_timeline,
    merged_timeline,
    record_all,
)
from .slo import DEFAULT_OBJECTIVES, Objective, evaluate as evaluate_slo
from .trace import (
    Span,
    Tracer,
    UNSAMPLED,
    export_merged_json,
    spans_to_trace_events,
    stitched_traces,
)

__all__ = [
    "DEFAULT_OBJECTIVES",
    "FleetScope",
    "FlightRecorder",
    "Objective",
    "ObsService",
    "ObsUnsupported",
    "Span",
    "Tracer",
    "UNSAMPLED",
    "attach_timeline",
    "evaluate_slo",
    "export_merged_json",
    "format_timeline",
    "hosts_timeline",
    "merged_timeline",
    "record_all",
    "spans_to_trace_events",
    "stitched_traces",
]
