"""Observability: end-to-end request tracing + per-shard flight
recorder (docs/OBSERVABILITY.md).

* :mod:`.trace` — the span model threaded through the proposal/read
  path with trace context carried in wire messages, plus the
  Chrome/Perfetto ``trace_event`` exporter;
* :mod:`.recorder` — the per-shard flight recorder ring buffers,
  dumped on demand (``NodeHost.dump_timeline``) and automatically when
  ``assert_recovery_sla`` trips, an audit gate fails, or the gateway
  sheds sustainedly (``gateway/admission.py``: overload is a state
  transition too — the moment the front door starts refusing work
  there must be a cross-host record of why).

Both are off by default (``NodeHostConfig.enable_tracing`` /
``enable_flight_recorder``); the disabled hot paths cost one attribute
load.
"""
from .recorder import (
    FlightRecorder,
    attach_timeline,
    format_timeline,
    hosts_timeline,
    merged_timeline,
    record_all,
)
from .trace import (
    Span,
    Tracer,
    UNSAMPLED,
    export_merged_json,
    spans_to_trace_events,
    stitched_traces,
)

__all__ = [
    "FlightRecorder",
    "Span",
    "Tracer",
    "UNSAMPLED",
    "attach_timeline",
    "export_merged_json",
    "format_timeline",
    "hosts_timeline",
    "merged_timeline",
    "record_all",
    "spans_to_trace_events",
    "stitched_traces",
]
