"""SLO layer: declarative objectives evaluated from fleet metric
deltas into burn-rate ledgers (docs/OBSERVABILITY.md "Fleet scope").

reference: the multiwindow burn-rate alerting idiom (SRE workbook ch.5)
— an objective owns an error budget, each observation window's
bad/good ratio divided by that budget is the window's burn rate, and a
burn rate above 1.0 means the budget is being spent faster than the
objective allows.  Here the windows are :class:`~.fleetscope.
FleetScope` poll deltas: every row says which objective burned, in
which wall window, across which processes — the triage answer a
production day's verdict owes its operator.

Objectives select COUNTER series (monotone, so a per-window delta is a
rate) or a histogram (latency objectives: the fraction of observations
past the bound).  Gauges are levels, not budgets, and are deliberately
not selectable.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..metrics import _base_name


@dataclass(frozen=True)
class Objective:
    """One declarative objective.

    ``kind``:

    * ``ratio`` — ``bad``/``good`` are counter selectors; the budget is
      the tolerated bad fraction of (bad + good);
    * ``latency`` — ``hist`` is a histogram base name; the budget is
      the tolerated fraction of observations slower than ``bound_s``;
    * ``event`` — ``bad`` is a counter selector; ANY delta burns (the
      burn rate is the event count itself — recovery-SLA misses have
      no denominator).

    A selector is a base name (matches every labelled series of that
    family) or a full labelled series name (exact match).
    """

    name: str
    kind: str
    bad: str = ""
    good: str = ""
    hist: str = ""
    bound_s: float = 0.0
    budget: float = 0.01
    description: str = ""


#: The catalog the scenario day reports evaluate (ISSUE 19): commit
#: latency, bounded-read overruns, admission sheds and recovery-SLA
#: misses.  Callers pass their own list to tighten/extend.
DEFAULT_OBJECTIVES: Sequence[Objective] = (
    Objective(
        name="commit_p99",
        kind="latency",
        hist="gateway_request_seconds",
        bound_s=0.5,
        budget=0.01,
        description="gateway request latency: <=1% of requests past "
                    "500ms",
    ),
    Objective(
        name="read_bound_overruns",
        kind="ratio",
        bad='nodehost_read_total{path="bounded_shed"}',
        good='nodehost_read_total{path="bounded"}',
        budget=0.05,
        description="bounded-staleness reads shed past the bound: <=5%",
    ),
    Objective(
        name="shed_ratio",
        kind="ratio",
        bad="gateway_shed_total",
        good="gateway_committed_total",
        budget=0.05,
        description="admission sheds vs commits: <=5%",
    ),
    Objective(
        name="recovery_sla_misses",
        kind="event",
        bad="churn_sla_violations_total",
        budget=0.0,
        description="recovery-SLA violations: any is a burn",
    ),
)


def _matches(series: str, selector: str) -> bool:
    if not selector:
        return False
    if "{" in selector:
        return series == selector
    return _base_name(series) == selector


def _sum_counter(delta: dict, selector: str) -> float:
    return float(sum(
        v for name, v in delta.get("counters", {}).items()
        if _matches(name, selector)
    ))


def _hist_over_bound(delta: dict, base: str, bound_s: float):
    """(observations past bound, total observations) from a window's
    histogram bucket deltas.  Bucket granularity rounds DOWN the
    overrun count (an observation counts as over only when its whole
    bucket lies past the bound) — burn rates err conservative."""
    over = total = 0.0
    for name, h in delta.get("histograms", {}).items():
        if _base_name(name) != base:
            continue
        bounds = h.get("bounds", ())
        buckets = h.get("buckets", ())
        total += float(h.get("count", 0))
        for i, b in enumerate(bounds):
            if b > bound_s and i < len(buckets):
                over += float(buckets[i])
        if len(buckets) > len(bounds):
            over += float(buckets[-1])  # +Inf overflow bucket
    return over, total


def _window_counts(o: Objective, window: dict):
    """(bad, good, procs-that-contributed-bad) for one poll window."""
    bad = good = 0.0
    procs: List[str] = []
    for key, delta in window.get("deltas", {}).items():
        if o.kind == "latency":
            b, total = _hist_over_bound(delta, o.hist, o.bound_s)
            g = max(0.0, total - b)
        else:
            b = _sum_counter(delta, o.bad)
            g = _sum_counter(delta, o.good) if o.good else 0.0
        bad += b
        good += g
        if b > 0:
            procs.append(key)
    return bad, good, procs


def _burn_rate(o: Objective, bad: float, good: float) -> float:
    if o.kind == "event" or o.budget <= 0.0:
        return bad
    total = bad + good
    if total <= 0:
        return 0.0
    return (bad / total) / o.budget


def evaluate(
    windows: Sequence[dict],
    objectives: Optional[Sequence[Objective]] = None,
    *,
    mark_horizon_s: float = 10.0,
) -> List[dict]:
    """Burn-rate rows, one per objective, from FleetScope poll windows
    (each ``{"t0", "t1", "marks", "deltas": {proc: metric deltas}}``).

    Each row aggregates the whole run and lists every BURNING window
    (burn rate > 1.0) with its wall bounds, contributing processes and
    the collector marks attributed to it — a mid-day kill window shows
    up attributed on exactly the objectives it burned.  Attribution
    looks BACK ``mark_horizon_s`` seconds from the burning window: a
    ``proc_kill`` mark lands in the short poll window where it was
    stamped, but the damage it causes (timeouts, sheds) burns the
    windows that close during the recovery — those later windows must
    still name their cause."""
    all_marks: List[list] = sorted(
        (list(m) for w in windows for m in w.get("marks", ())),
        key=lambda m: float(m[0]),
    )
    rows: List[dict] = []
    for o in objectives if objectives is not None else DEFAULT_OBJECTIVES:
        total_bad = total_good = 0.0
        procs: set = set()
        burn_windows: List[dict] = []
        for w in windows:
            bad, good, wprocs = _window_counts(o, w)
            total_bad += bad
            total_good += good
            procs.update(wprocs)
            rate = _burn_rate(o, bad, good)
            if rate > 1.0:
                t0 = float(w.get("t0", 0.0))
                t1 = float(w.get("t1", 0.0))
                burn_windows.append({
                    "t0": round(t0, 6),
                    "t1": round(t1, 6),
                    "bad": bad,
                    "good": good,
                    "burn_rate": round(rate, 4),
                    "procs": sorted(wprocs),
                    "marks": [
                        m for m in all_marks
                        if t0 - mark_horizon_s <= float(m[0]) <= t1
                    ],
                })
        rate = _burn_rate(o, total_bad, total_good)
        total = total_bad + total_good
        rows.append({
            "objective": o.name,
            "kind": o.kind,
            "budget": o.budget,
            "bad": total_bad,
            "good": total_good,
            "ratio": round(total_bad / total, 6) if total else 0.0,
            "burn_rate": round(rate, 4),
            "burning": bool(burn_windows),
            "windows": burn_windows,
            "procs": sorted(procs),
            "description": o.description,
        })
    return rows
