"""Per-shard flight recorder: fixed-size ring buffers of state
transitions, dumpable on demand and dumped automatically when a
recovery SLA trips or an audit gate fails.

reference: aviation FDR semantics — always on, bounded memory, read
AFTER the incident.  The PR 3 quiesce-parked-election liveness bug took
a bespoke harness to localize precisely because no timeline of
per-shard state existed; this is that timeline, recorded continuously:

* leader changes (``NodeHost._on_leader_updated``),
* membership ops / snapshot send + recv / log compaction (via the
  ``EventFanout`` tap — every ISystemEventListener callback),
* quiesce park / unpark (the host ticker and ``_wake_node``),
* fault-plane activations/heals and churn actions (via
  ``FaultController.install_recorder``).

Events are ``(monotonic_ts, host, shard_id, kind, detail)`` tuples in a
per-shard ``deque(maxlen=...)`` — recording is a lock + append, old
events fall off, a recorder can run for weeks.  ``shard_id 0`` is the
global lane (host-level and fault-plane events).

Internally each ring entry additionally carries a recorder-wide
monotone sequence number (assigned under the record lock) so
:meth:`FlightRecorder.tail` gives remote collectors an EXACT resume
cursor: seq gaps in a slice are events that fell off a ring, a reply
whose ``epoch`` changed (or whose seq regressed) is a restarted
process.  ``events()`` strips the seq — the public Event tuple shape
is unchanged.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from random import getrandbits
from typing import Dict, List, Optional, Tuple

Event = Tuple[float, str, int, str, str]


class FlightRecorder:
    def __init__(
        self,
        host: str = "",
        capacity: int = 256,
        global_capacity: int = 1024,
    ):
        self.host = host
        self.capacity = capacity
        self._lock = threading.Lock()
        self._rings: Dict[int, deque] = {}
        self._global: deque = deque(maxlen=global_capacity)
        self.recorded = 0
        # restart identity: a collector that sees a different epoch (or
        # a regressed seq) on the same address knows the rings belong
        # to a NEW process incarnation and resets its cursor
        self.epoch = getrandbits(63) | 1
        self._seq = 0

    def record(self, shard_id: int, kind: str, detail: str = "") -> None:
        ts = time.monotonic()
        with self._lock:
            self._seq += 1
            self.recorded += 1
            e = (self._seq, ts, self.host, int(shard_id), kind,
                 str(detail))
            if shard_id:
                ring = self._rings.get(shard_id)
                if ring is None:
                    ring = self._rings[shard_id] = deque(maxlen=self.capacity)
                ring.append(e)
            else:
                self._global.append(e)

    def events(self, shard_id: Optional[int] = None) -> List[Event]:
        """Chronological events: one shard's ring merged with the global
        lane, or every ring when ``shard_id`` is None."""
        with self._lock:
            if shard_id is None:
                out = [e[1:] for ring in self._rings.values() for e in ring]
            else:
                out = [e[1:] for e in self._rings.get(shard_id, ())]
            out.extend(e[1:] for e in self._global)
        out.sort(key=lambda e: e[0])
        return out

    def tail(self, cursor: int = 0, *, limit: int = 256) -> dict:
        """Bounded ring slice past a client-held cursor, for remote
        collectors (``RPC_OBS_RECORDER``): the oldest ``limit`` events
        whose seq is past ``cursor``, oldest first, each as
        ``[seq, ts, host, shard_id, kind, detail]``.  ``next_cursor``
        resumes the poll exactly; ``dropped`` counts seqs in the window
        that already fell off a ring (the wrap the cursor can't hide);
        ``epoch``/``seq`` let the collector detect a restarted process
        (new epoch, or seq below its cursor)."""
        with self._lock:
            rows = [e for ring in self._rings.values()
                    for e in ring if e[0] > cursor]
            rows.extend(e for e in self._global if e[0] > cursor)
            seq = self._seq
        rows.sort(key=lambda e: e[0])
        dropped = (rows[-1][0] - cursor - len(rows)) if rows else 0
        rows = rows[:max(0, int(limit))]
        return {
            "epoch": self.epoch,
            "seq": seq,
            "next_cursor": rows[-1][0] if rows else cursor,
            "dropped": dropped,
            "events": [list(e) for e in rows],
        }

    def dump(self, shard_id: Optional[int] = None) -> str:
        """Human-readable timeline (the auto-dump format)."""
        return (
            format_timeline(self.events(shard_id))
            or "(flight recorder empty)"
        )


def merged_timeline(
    recorders=(),
    tracers=(),
    shard_id: Optional[int] = None,
) -> List[Event]:
    """One chronological timeline across hosts: flight-recorder events
    merged with span starts/ends/annotations from the tracers (spans
    appear as ``span:<name>`` / ``span-end:<name>`` events).  This is
    the view the churn acceptance criterion reads: the injected
    leader-kill event lands between the victim shard's last pre-kill
    apply span and its first post-re-election commit annotation."""
    out: List[Event] = []
    for r in recorders:
        if r is not None:
            out.extend(r.events(shard_id))
    for t in tracers:
        if t is None:
            continue
        for s in t.spans():
            if shard_id is not None and s.shard_id not in (0, shard_id):
                continue
            out.append(
                (s.start, s.host, s.shard_id, f"span:{s.name}",
                 f"trace={s.trace_id:x}")
            )
            for ts, label in list(s.annotations):
                out.append(
                    (ts, s.host, s.shard_id, f"ann:{label}",
                     f"trace={s.trace_id:x}")
                )
            if s.end_ts:
                out.append(
                    (s.end_ts, s.host, s.shard_id, f"span-end:{s.name}",
                     f"trace={s.trace_id:x} status={s.status}")
                )
    out.sort(key=lambda e: e[0])
    return out


def format_timeline(events: List[Event]) -> str:
    return "\n".join(
        f"[{t:.6f}] {host} shard={sid} {kind} {detail}".rstrip()
        for t, host, sid, kind, detail in events
    )


def attach_timeline(
    exc,
    hosts,
    shard_id: Optional[int] = None,
    label: str = "",
    log=None,
) -> "BaseException":
    """The shared auto-dump: attach the merged cross-host timeline to
    ``exc.timeline`` and log an 80-line tail.  Serves both failure
    gates (``assert_recovery_sla`` violations, ``assert_audit_ok``) —
    best-effort by contract: a dump failure must never mask the verdict
    being raised, so this never raises and always returns ``exc``.
    ``hosts`` is a {key: NodeHost} dict or an iterable of NodeHosts."""
    if log is None:
        from ..logger import get_logger

        log = get_logger("obs")
    try:
        hs = hosts.values() if hasattr(hosts, "values") else hosts
        text = hosts_timeline(hs, shard_id=shard_id)
    except Exception:  # noqa: BLE001 — observability is best-effort
        log.exception("flight-recorder auto-dump failed")
        return exc
    if text:
        exc.timeline = text
        tail = "\n".join(text.splitlines()[-80:])
        log.error(
            "%s — flight-recorder timeline (tail):\n%s",
            label or type(exc).__name__, tail,
        )
    return exc


def record_all(hosts, shard_id: int, kind: str, detail: str = "") -> None:
    """Stamp one marker event into EVERY given host's flight recorder
    (hosts without a recorder contribute nothing; never raises — same
    best-effort contract as :func:`attach_timeline`).  The scenario
    orchestrator uses this for phase boundaries: a post-incident dump
    must show WHICH production-day phase the cluster was in when the
    state transitions around the failure happened (docs/SCENARIO.md)."""
    hs = hosts.values() if hasattr(hosts, "values") else hosts
    for nh in hs:
        rec = getattr(nh, "recorder", None)
        if rec is None:
            continue
        try:
            rec.record(shard_id, kind, detail)
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass


def hosts_timeline(hosts, shard_id: Optional[int] = None) -> str:
    """The auto-dump entry point (``assert_recovery_sla`` violations,
    audit-gate failures): one formatted cross-host timeline from every
    given NodeHost's flight recorder AND tracer.  Hosts with
    observability disabled contribute nothing; with it disabled
    everywhere the result is the empty string (callers skip logging)."""
    recorders = [getattr(nh, "recorder", None) for nh in hosts]
    tracers = [getattr(nh, "tracer", None) for nh in hosts]
    if not any(r is not None for r in recorders) and not any(
        t is not None for t in tracers
    ):
        return ""
    return format_timeline(
        merged_timeline(recorders=recorders, tracers=tracers,
                        shard_id=shard_id)
    )
