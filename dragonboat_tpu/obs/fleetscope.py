"""Fleet scope: the cross-process telemetry plane
(docs/OBSERVABILITY.md "Fleet scope").

reference: dragonboat ships fleet-level visibility via
``raftio.ISystemEventListener`` + per-NodeHost metrics because
multi-process Raft is undebuggable without a merged view; Dapper-style
context propagation answers the RPC boundary.  This module is both
halves for the PR 16-18 fleet:

* :class:`ObsService` — the server side of ``RPC_OP_OBS``
  (gateway/rpc.py dispatches here): ``metrics_snapshot`` (structured
  :meth:`~dragonboat_tpu.metrics.MetricsRegistry.snapshot`, tagged
  with host/pid/uptime), ``recorder_tail`` and ``trace_spans``
  (bounded ring slices past a client-held cursor — every slice passes
  an EXPLICIT limit; raftlint's obs-bound rule bans unbounded
  replies).
* :class:`FleetScope` — the collector: polls every fleet process
  (remote handles over the wire, in-proc hosts directly), rebases
  remote monotonic timestamps onto the collector's clock, merges
  recorder events + span starts/ends into ONE cross-process timeline
  (reusing :func:`~.recorder.merged_timeline`'s interleave), survives
  process death by keeping the dead process's last tail and stamping
  the gap (``obs_gap``/``obs_gap_end`` marker events), detects
  restarts by epoch change / sequence regression, and turns per-poll
  metric deltas into :mod:`.slo` burn-rate rows
  (:meth:`FleetScope.slo_report`).

Degrade matrix: a process answering ``RPC_ERR "unknown op 7"``
predates the obs surface — the scope marks it ``no_obs`` and the rest
of the fleet still merges; a process that stops answering at all keeps
its last tail with the gap marked.  Everything here is best-effort
observability: no poll failure ever propagates into the planes being
observed.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..logger import get_logger
from . import slo as slo_mod
from .recorder import Event, format_timeline, merged_timeline

_log = get_logger("obs")


class ObsUnsupported(Exception):
    """The polled process predates RPC_OP_OBS (old server binary)."""


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
class ObsService:
    """Answers the three obs queries for ONE process's NodeHost.

    Works against anything exposing the NodeHost obs surface
    (``metrics``/``recorder``/``tracer`` attributes, any of them
    optional) — the RpcServer binds one per ingress, the FleetScope
    wraps one directly for in-proc targets.  Every reply is tagged with
    the process identity (host/nhid/pid/uptime) plus ``mono``, the
    server's monotonic clock at snapshot time, which the collector uses
    to rebase remote timestamps (cross-process clocks don't share an
    origin)."""

    def __init__(self, nh):
        self._nh = nh
        self._t0 = time.monotonic()

    def _identity(self) -> dict:
        nh = self._nh
        host = ""
        fn = getattr(nh, "raft_address", None)
        if callable(fn):
            try:
                host = fn() or ""
            except Exception:  # noqa: BLE001 — identity is best-effort
                host = ""
        if not host:
            host = str(getattr(nh, "host", "") or "")
        up = getattr(nh, "uptime_s", None)
        if not isinstance(up, (int, float)):
            up = time.monotonic() - self._t0
        return {
            "host": host,
            "nhid": str(getattr(nh, "nodehost_id", "") or ""),
            "pid": os.getpid(),
            "uptime_s": round(float(up), 3),
            "mono": time.monotonic(),
        }

    def metrics_snapshot(self) -> dict:
        out = self._identity()
        m = getattr(self._nh, "metrics", None)
        snap = getattr(m, "snapshot", None)
        out["metrics"] = snap() if callable(snap) else {}
        return out

    def recorder_tail(self, cursor: int, *, limit: int) -> dict:
        out = self._identity()
        rec = getattr(self._nh, "recorder", None)
        if rec is None:
            out.update({"enabled": False, "epoch": 0, "seq": 0,
                        "next_cursor": cursor, "dropped": 0, "events": []})
            return out
        out["enabled"] = True
        out.update(rec.tail(cursor, limit=limit))
        return out

    def trace_spans(self, cursor: int, *, limit: int) -> dict:
        out = self._identity()
        tr = getattr(self._nh, "tracer", None)
        if tr is None:
            out.update({"enabled": False, "epoch": 0, "seq": 0,
                        "next_cursor": cursor, "dropped": 0, "spans": []})
            return out
        out["enabled"] = True
        out.update(tr.finished_tail(cursor, limit=limit))
        return out


# ---------------------------------------------------------------------------
# collector side
# ---------------------------------------------------------------------------
class SpanRecord:
    """A finished span as collected over the wire — duck-types exactly
    what :func:`~.recorder.merged_timeline` and the stitch predicates
    read off a live :class:`~.trace.Span` (start/end_ts in COLLECTOR
    monotonic time after rebase)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "host",
                 "shard_id", "start", "end_ts", "status", "annotations",
                 "seq")

    def __init__(self, d: dict, offset: float):
        self.trace_id = int(d.get("trace_id", 0))
        self.span_id = int(d.get("span_id", 0))
        self.parent_id = int(d.get("parent_id", 0))
        self.name = str(d.get("name", ""))
        self.host = str(d.get("host", ""))
        self.shard_id = int(d.get("shard_id", 0))
        self.start = float(d.get("start", 0.0)) + offset
        end = float(d.get("end", 0.0))
        self.end_ts = end + offset if end else 0.0
        self.status = str(d.get("status", ""))
        self.annotations: List[Tuple[float, str]] = [
            (float(ts) + offset, str(label))
            for ts, label in d.get("ann", ())
        ]
        self.seq = int(d.get("seq", 0))


class _EventsView:
    """FlightRecorder-shaped view over already-collected events, so the
    fleet merge genuinely reuses recorder.merged_timeline."""

    def __init__(self, events: List[Event]):
        self._events = events

    def events(self, shard_id: Optional[int] = None) -> List[Event]:
        if shard_id is None:
            return list(self._events)
        return [e for e in self._events if e[2] in (0, shard_id)]


class _SpansView:
    """Tracer-shaped view over collected SpanRecords (same reuse)."""

    def __init__(self, spans: List[SpanRecord]):
        self._spans = spans

    def spans(self) -> List[SpanRecord]:
        return list(self._spans)


class _RemoteTarget:
    """Adapter over a RemoteHostHandle's ``obs_query`` method family."""

    def __init__(self, handle):
        self._h = handle

    def metrics(self) -> dict:
        return self._h.obs_query("metrics")

    def recorder_tail(self, cursor: int, *, limit: int) -> dict:
        return self._h.obs_query("recorder", cursor=cursor, limit=limit)

    def trace_spans(self, cursor: int, *, limit: int) -> dict:
        return self._h.obs_query("spans", cursor=cursor, limit=limit)


class _LocalTarget:
    """Adapter over an in-proc NodeHost (or anything with the obs
    attribute surface) — the in-proc production day's path."""

    def __init__(self, nh):
        self._svc = ObsService(nh)

    def metrics(self) -> dict:
        return self._svc.metrics_snapshot()

    def recorder_tail(self, cursor: int, *, limit: int) -> dict:
        return self._svc.recorder_tail(cursor, limit=limit)

    def trace_spans(self, cursor: int, *, limit: int) -> dict:
        return self._svc.trace_spans(cursor, limit=limit)


class _ProcScope:
    """Per-process collector state: cursors, epochs, the kept tail."""

    def __init__(self, key: str, target, keep: int):
        self.key = key
        self.target = target
        self.no_obs = False
        self.dead = False
        self.gap_open = False
        self.restarts = 0
        self.rec_epoch = 0
        self.rec_cursor = 0
        self.span_epoch = 0
        self.span_cursor = 0
        self.offset = 0.0
        self.identity: dict = {}
        self.prev: Optional[dict] = None
        self.last: Optional[dict] = None
        # the kept tails are bounded like the rings they mirror; a dead
        # process's tail stays here — that survival is the point
        self.events: List[Event] = []
        self.spans: List[SpanRecord] = []
        self._keep = keep

    def _trim(self) -> None:
        if len(self.events) > self._keep:
            del self.events[:len(self.events) - self._keep]
        if len(self.spans) > self._keep:
            del self.spans[:len(self.spans) - self._keep]

    @property
    def host(self) -> str:
        return str(self.identity.get("host") or self.key)


class FleetScope:
    """The fleet collector (see module docstring).

    ``add_process`` accepts a RemoteHostHandle (polled over
    ``RPC_OP_OBS``) or an in-proc NodeHost-like object (polled
    directly) — a mixed fleet (networked workers + the parent's own
    gateway process) merges into one timeline.  ``poll()`` is one
    sweep; ``start_poller`` runs it on an interval.  Collector marks
    (:meth:`mark`) land on the timeline AND on the poll window that
    closes over them, which is how a kill window gets attributed to
    the SLO rows that burned during it."""

    def __init__(self, *, limit: int = 256, keep: int = 4096,
                 objectives=None, max_windows: int = 1024):
        self._limit = limit
        self._keep = keep
        self._objectives = objectives
        self._max_windows = max_windows
        self._lock = threading.RLock()
        self._procs: Dict[str, _ProcScope] = {}
        self._pending_marks: List[Event] = []
        self.marks: List[Event] = []
        self.windows: List[dict] = []
        self.polls = 0
        self.reply_bytes = 0
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None

    # -- membership -------------------------------------------------------
    def add_process(self, key: str, target) -> None:
        """Register one fleet process.  ``target``: RemoteHostHandle
        (has ``obs_query``) or an in-proc NodeHost-like object."""
        adapter = (
            _RemoteTarget(target) if hasattr(target, "obs_query")
            else _LocalTarget(target)
        )
        with self._lock:
            self._procs[key] = _ProcScope(key, adapter, self._keep)

    def mark(self, kind: str, detail: str = "") -> None:
        """Stamp a collector-lane marker (phase boundary, kill window)
        onto the merged timeline and the current poll window."""
        e: Event = (time.monotonic(), "fleetscope", 0, str(kind),
                    str(detail))
        with self._lock:
            self.marks.append(e)
            self._pending_marks.append(e)

    # -- polling ----------------------------------------------------------
    def poll(self) -> dict:
        """One sweep over every process: metrics deltas, recorder and
        span tails, gap/restart bookkeeping.  Never raises — a dead or
        obs-less process is recorded, not fatal."""
        t0 = time.monotonic()
        with self._lock:
            procs = list(self._procs.values())
            marks, self._pending_marks = self._pending_marks, []
        deltas: Dict[str, dict] = {}
        polled = dead = 0
        for p in procs:
            try:
                self._poll_one(p)
            except ObsUnsupported:
                if not p.no_obs:
                    p.no_obs = True
                    _log.warning(
                        "fleetscope: %s predates the obs op (no-obs)",
                        p.key,
                    )
                continue
            except Exception as e:  # noqa: BLE001 — dead/unreachable
                self._mark_gap(p, e)
                dead += 1
                continue
            polled += 1
            d = _metrics_delta(p.prev, p.last)
            if d:
                deltas[p.key] = d
        window = {
            "t0": t0,
            "t1": time.monotonic(),
            "marks": [list(m) for m in marks],
            "deltas": deltas,
        }
        with self._lock:
            self.windows.append(window)
            if len(self.windows) > self._max_windows:
                del self.windows[:len(self.windows) - self._max_windows]
            self.polls += 1
        return {
            "polled": polled,
            "dead": dead,
            "no_obs": sum(1 for p in procs if p.no_obs),
        }

    def _poll_one(self, p: _ProcScope) -> None:
        t_req = time.monotonic()
        m = p.target.metrics()
        t_resp = time.monotonic()
        self._count_bytes(m)
        # rebase: the remote stamped its monotonic clock between our
        # request and its reply — the midpoint estimate bounds the
        # offset error at half the RTT
        remote_mono = float(m.get("mono", 0.0) or 0.0)
        p.offset = ((t_req + t_resp) / 2.0 - remote_mono
                    if remote_mono else 0.0)
        p.identity = {
            k: m.get(k) for k in ("host", "nhid", "pid", "uptime_s")
        }
        if p.gap_open:
            p.gap_open = False
            p.events.append((
                time.monotonic(), p.host, 0, "obs_gap_end",
                f"pid={m.get('pid')} uptime={m.get('uptime_s')}s",
            ))
        p.dead = False

        rt = p.target.recorder_tail(p.rec_cursor, limit=self._limit)
        self._count_bytes(rt)
        if rt.get("enabled", True) and rt.get("epoch"):
            if p.rec_epoch and (
                rt["epoch"] != p.rec_epoch
                or int(rt.get("seq", 0)) < p.rec_cursor
            ):
                # restarted process: fresh rings under the same address
                # — note it, reset the cursor and take the new tail
                # from its beginning
                p.restarts += 1
                p.events.append((
                    time.monotonic(), p.host, 0, "obs_restart",
                    f"epoch {p.rec_epoch:x}->{int(rt['epoch']):x}",
                ))
                p.rec_cursor = 0
                rt = p.target.recorder_tail(0, limit=self._limit)
                self._count_bytes(rt)
            p.rec_epoch = int(rt["epoch"])
            if rt.get("dropped"):
                p.events.append((
                    time.monotonic(), p.host, 0, "obs_dropped",
                    f"{rt['dropped']} events fell off the ring between "
                    f"polls",
                ))
            for row in rt.get("events", ()):
                _seq, ts, host, sid, kind, detail = row
                p.events.append((
                    float(ts) + p.offset, str(host), int(sid), str(kind),
                    str(detail),
                ))
            p.rec_cursor = int(rt.get("next_cursor", p.rec_cursor))

        st = p.target.trace_spans(p.span_cursor, limit=self._limit)
        self._count_bytes(st)
        if st.get("enabled", True) and st.get("epoch"):
            if p.span_epoch and (
                st["epoch"] != p.span_epoch
                or int(st.get("seq", 0)) < p.span_cursor
            ):
                p.span_cursor = 0
                st = p.target.trace_spans(0, limit=self._limit)
                self._count_bytes(st)
            p.span_epoch = int(st["epoch"])
            for d in st.get("spans", ()):
                p.spans.append(SpanRecord(d, p.offset))
            p.span_cursor = int(st.get("next_cursor", p.span_cursor))

        p.prev, p.last = p.last, m
        p._trim()

    def _count_bytes(self, reply: dict) -> None:
        n = reply.pop("bytes", 0) if isinstance(reply, dict) else 0
        if n:
            self.reply_bytes += int(n)

    def _mark_gap(self, p: _ProcScope, exc: BaseException) -> None:
        p.dead = True
        if not p.gap_open:
            p.gap_open = True
            p.events.append((
                time.monotonic(), p.host, 0, "obs_gap",
                f"poll failed: {type(exc).__name__}: {exc}",
            ))

    # -- background poller ------------------------------------------------
    def start_poller(self, interval: float = 0.25) -> None:
        def _main() -> None:
            while not self._stop.wait(interval):
                try:
                    self.poll()
                except Exception:  # noqa: BLE001 — observability is
                    # best-effort; the poller must outlive a bad sweep
                    _log.exception("fleetscope poll failed")

        t = threading.Thread(target=_main, daemon=True,
                             name="tpu-fleetscope")
        self._poller = t
        t.start()

    def close(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2.0)
            self._poller = None

    # -- merged views -----------------------------------------------------
    def merged_timeline(self, shard_id: Optional[int] = None) -> List[Event]:
        """ONE chronological timeline across every polled process —
        recorder events interleaved with span start/end pseudo-events
        via recorder.merged_timeline, collector marks included.  Dead
        processes contribute their last collected tail plus the
        ``obs_gap`` marker (the acceptance view: the SIGKILLed
        leader's silence sits between its last pre-kill events and the
        survivors' re-election)."""
        with self._lock:
            recs = [_EventsView(list(p.events))
                    for p in self._procs.values()]
            recs.append(_EventsView(list(self.marks)))
            trs = [_SpansView(list(p.spans))
                   for p in self._procs.values()]
        return merged_timeline(recorders=recs, tracers=trs,
                               shard_id=shard_id)

    def dump(self, shard_id: Optional[int] = None) -> str:
        return (
            format_timeline(self.merged_timeline(shard_id))
            or "(fleet scope empty)"
        )

    def stitched_traces(self) -> Dict[int, List[SpanRecord]]:
        """trace_id -> collected spans across every process (the
        cross-process analogue of trace.stitched_traces)."""
        by: Dict[int, List[SpanRecord]] = {}
        with self._lock:
            spans = [s for p in self._procs.values() for s in p.spans]
        for s in spans:
            by.setdefault(s.trace_id, []).append(s)
        return by

    def cross_process_stitches(self) -> int:
        """Traces whose spans span >1 distinct host — the smoke's
        acceptance predicate for RPC trace stitching."""
        return sum(
            1 for spans in self.stitched_traces().values()
            if len({s.host for s in spans}) > 1
        )

    # -- reports ----------------------------------------------------------
    def proc_report(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "key": p.key,
                    "host": p.host,
                    "pid": p.identity.get("pid"),
                    "no_obs": p.no_obs,
                    "dead": p.dead,
                    "restarts": p.restarts,
                    "events": len(p.events),
                    "spans": len(p.spans),
                }
                for p in self._procs.values()
            ]

    def slo_report(self, objectives=None) -> List[dict]:
        """Burn-rate rows over every poll window so far (obs/slo.py);
        the scenario runners attach these to the DayReport."""
        with self._lock:
            windows = list(self.windows)
        return slo_mod.evaluate(
            windows,
            objectives=(objectives if objectives is not None
                        else self._objectives),
        )


def _metrics_delta(prev: Optional[dict], cur: Optional[dict]) -> dict:
    """Window delta between two tagged metric snapshots: monotone
    series (counters, histogram count/sum/buckets) are differenced,
    gauges carried as levels.  Zero-delta series are omitted so a
    quiet window costs almost nothing to keep."""
    if not cur:
        return {}
    pm = (prev or {}).get("metrics") or {}
    cm = cur.get("metrics") or {}
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    pc = pm.get("counters") or {}
    for name, e in (cm.get("counters") or {}).items():
        d = e.get("value", 0) - (pc.get(name) or {}).get("value", 0)
        if d:
            out["counters"][name] = d
    for name, e in (cm.get("gauges") or {}).items():
        out["gauges"][name] = e.get("value", 0.0)
    ph = pm.get("histograms") or {}
    for name, e in (cm.get("histograms") or {}).items():
        pe = ph.get(name) or {}
        count_d = e.get("count", 0) - pe.get("count", 0)
        if not count_d:
            continue
        pb = pe.get("buckets") or [0] * len(e.get("buckets") or ())
        out["histograms"][name] = {
            "bounds": list(e.get("bounds") or ()),
            "buckets": [
                c - p for c, p in zip(e.get("buckets") or (), pb)
            ],
            "count": count_d,
            "sum": e.get("sum", 0.0) - pe.get("sum", 0.0),
        }
    return out if any(out.values()) else {}
