"""Dependency-light request tracing: spans, annotations, Perfetto export.

reference: the reference leans on Go pprof + expvar counters for
visibility (SURVEY §5.1); counters answer "how many / how fast" but not
"where did these 4 seconds go?" for ONE proposal.  This module is the
missing half: a minimal span model (no OpenTelemetry dependency — the
container bakes nothing in) threaded through the proposal/read path

    client -> nodehost.propose -> request queue -> engine step batch
           -> raft append/replicate -> commit -> rsm apply
           -> future completion

with trace context carried inside wire messages (``pb.Message.trace_id``
/ ``span_id``; transport/wire.py encodes them) so a follower's append
span stitches into the SAME cross-host trace as the leader's proposal.

Cost contract: a disabled tracer is ``None`` on every hot object — the
hot paths pay one attribute load and a falsy test, nothing else
(verified by scripts/obs_smoke.sh's bench guard).  An enabled tracer
records into a bounded ring (old traces fall off; a tracer can run
forever without growing) and sampling (``trace_sample_rate``) bounds
the per-request cost at high rates.

Timebase: ``time.monotonic()`` — one clock per process.  All-in-one-
process clusters (the test/bench topology) merge exactly; cross-process
merges are subject to clock skew between processes (noted in
docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import json
import threading
import time
import weakref
from collections import deque
from random import Random
from typing import Dict, List, Optional, Tuple


# sentinel parent for "the root made a sampling decision and the answer
# was NO" — distinct from parent=None ("no caller-held trace"), which
# lets the callee start its own root.  Without it, an unsampled
# client:propose_with_retry root would be re-sampled by nodehost.propose
# (a second independent draw, violating the sampled-once-at-the-root
# contract and inflating the effective rate).
UNSAMPLED = object()


class Span:
    """One timed operation in a trace.  ``annotate`` appends timestamped
    labels (list.append is atomic under the GIL — annotations may come
    from producer, step and apply threads); ``end`` is idempotent and
    hands the span to the tracer's ring."""

    __slots__ = (
        "tracer", "trace_id", "span_id", "parent_id", "name", "host",
        "shard_id", "start", "end_ts", "status", "annotations", "seq",
        "__weakref__",
    )

    def __init__(self, tracer, trace_id, span_id, parent_id, name,
                 host, shard_id):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.host = host
        self.shard_id = shard_id
        self.start = time.monotonic()
        self.end_ts = 0.0
        self.status = ""
        self.annotations: List[Tuple[float, str]] = []
        # finished-ring position, assigned in end() under the tracer
        # lock: the cursor remote collectors resume finished_tail by
        self.seq = 0

    def annotate(self, label: str) -> None:
        self.annotations.append((time.monotonic(), label))

    def end(self, status: str = "ok") -> None:
        # the claim must be atomic: the request path sanctions racing
        # notifies (request.py's drop_all can sweep between applied()'s
        # two lock holds) — a check-then-act here would ring the span
        # twice
        tracer = self.tracer
        with tracer._lock:
            if self.end_ts:
                return
            self.end_ts = time.monotonic()
            self.status = status
            tracer._fin_seq += 1
            self.seq = tracer._fin_seq
            tracer._live.discard(self)
            tracer._spans.append(self)

    @property
    def ended(self) -> bool:
        return self.end_ts != 0.0


class Tracer:
    """Per-NodeHost span factory + bounded finished-span ring.

    ``start_trace`` makes the per-request sampling decision (one RNG
    draw) and returns ``None`` for unsampled requests — callers
    propagate the ``None`` so the rest of the path costs nothing.
    ``start_span`` never samples: it continues a trace whose context
    arrived from elsewhere (a wire message), which was already sampled
    at its root.
    """

    def __init__(
        self,
        host: str = "",
        sample_rate: float = 1.0,
        capacity: int = 8192,
        seed: Optional[int] = None,
    ):
        self.host = host
        self.sample_rate = sample_rate
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        # open spans, weakly held: a hung request's span must show up
        # in dumps/exports (the auto-dump exists for exactly those),
        # but a span whose holder dropped it without end() must not
        # accumulate forever
        self._live: "weakref.WeakSet[Span]" = weakref.WeakSet()
        self._rng = Random(seed)
        self.started = 0
        self.unsampled = 0
        # finished-ring sequencing for remote tails (same restart-
        # detection contract as FlightRecorder.epoch/_seq)
        self._fin_seq = 0
        self.epoch = self._rng.getrandbits(63) | 1

    def _id(self) -> int:
        # caller holds self._lock.  63-bit so ids ride u64 wire fields
        # with headroom; nonzero (0 means "no trace context" on the
        # wire)
        return self._rng.getrandbits(63) | 1

    def start_trace(self, name: str, shard_id: int = 0) -> Optional[Span]:
        # one lock acquisition per root span: sampling draw, both ids,
        # counters and live-set registration all under the same hold
        # (this is the traced-propose hot path, contending with
        # Span.end from apply workers)
        with self._lock:
            if (
                self.sample_rate < 1.0
                and not self._rng.random() < self.sample_rate
            ):
                self.unsampled += 1
                return None
            self.started += 1
            s = Span(
                self, self._id(), self._id(), 0, name, self.host, shard_id
            )
            self._live.add(s)
        return s

    def start_span(
        self, name: str, trace_id: int, parent_id: int, shard_id: int = 0
    ) -> Span:
        with self._lock:
            s = Span(
                self, trace_id, self._id(), parent_id, name, self.host,
                shard_id,
            )
            self._live.add(s)
        return s

    def spans(self) -> List[Span]:
        """Finished spans (the ring) plus still-open ones — an open
        span is exported with status "open" / no span-end marker, so a
        request stuck mid-path is visible in the very dump that fires
        because it is stuck."""
        with self._lock:
            return list(self._spans) + list(self._live)

    def finished_tail(self, cursor: int = 0, *, limit: int = 256) -> dict:
        """Bounded finished-span ring slice past a client-held cursor
        (``RPC_OBS_SPANS``): the oldest ``limit`` spans ended after
        ``cursor``, serialized as plain dicts.  Mirrors
        ``FlightRecorder.tail``'s cursor/epoch/dropped contract; open
        spans are NOT included (they have no seq yet — a collector sees
        them on the poll after they end)."""
        with self._lock:
            rows = [s for s in self._spans if s.seq > cursor]
            seq = self._fin_seq
        rows.sort(key=lambda s: s.seq)
        dropped = (rows[-1].seq - cursor - len(rows)) if rows else 0
        rows = rows[:max(0, int(limit))]
        return {
            "epoch": self.epoch,
            "seq": seq,
            "next_cursor": rows[-1].seq if rows else cursor,
            "dropped": dropped,
            "spans": [
                {
                    "seq": s.seq,
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    "name": s.name,
                    "host": s.host,
                    "shard_id": s.shard_id,
                    "start": s.start,
                    "end": s.end_ts,
                    "status": s.status,
                    "ann": [[ts, label] for ts, label in list(s.annotations)],
                }
                for s in rows
            ],
        }

    # -- export ----------------------------------------------------------
    def trace_events(self) -> List[dict]:
        """Chrome/Perfetto ``trace_event`` records (one complete event
        per span, one instant event per annotation).  Open either in
        ui.perfetto.dev or chrome://tracing."""
        return spans_to_trace_events(self.spans())

    def export_json(self) -> str:
        return json.dumps(
            {"traceEvents": self.trace_events(), "displayTimeUnit": "ms"}
        )


def spans_to_trace_events(spans: List[Span]) -> List[dict]:
    """The Chrome ``trace_event`` encoding shared by Tracer.export_json
    and multi-host merges: pid = host, tid = shard, ts/dur in
    microseconds of the process-wide monotonic clock."""
    out: List[dict] = []
    for s in spans:
        end = s.end_ts or time.monotonic()
        out.append(
            {
                "ph": "X",
                "name": s.name,
                "cat": "raft",
                "pid": s.host,
                "tid": f"shard-{s.shard_id}",
                "ts": s.start * 1e6,
                "dur": max(0.0, end - s.start) * 1e6,
                "args": {
                    "trace_id": f"{s.trace_id:x}",
                    "span_id": f"{s.span_id:x}",
                    "parent_id": f"{s.parent_id:x}" if s.parent_id else "",
                    "status": s.status or "open",
                },
            }
        )
        for ts, label in list(s.annotations):
            out.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": label,
                    "cat": "raft",
                    "pid": s.host,
                    "tid": f"shard-{s.shard_id}",
                    "ts": ts * 1e6,
                    "args": {"trace_id": f"{s.trace_id:x}"},
                }
            )
    return out


def export_merged_json(tracers) -> str:
    """One Perfetto file for a whole (in-process) cluster: the per-host
    pid lanes make the cross-host stitch visible as same-trace_id spans
    in different lanes."""
    events: List[dict] = []
    for t in tracers:
        if t is not None:
            events.extend(t.trace_events())
    return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})


def stitched_traces(tracers) -> Dict[int, List[Span]]:
    """trace_id -> spans from EVERY given tracer; a trace whose spans
    carry more than one distinct host is a cross-host stitch (the
    obs-smoke acceptance predicate)."""
    by_trace: Dict[int, List[Span]] = {}
    for t in tracers:
        if t is None:
            continue
        for s in t.spans():
            by_trace.setdefault(s.trace_id, []).append(s)
    return by_trace
