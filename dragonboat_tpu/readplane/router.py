"""Replica-aware read routing: power-of-two-choices on observed p99.

The gateway learns each shard's full replica set from the gossip-fed
collector view (gateway/routing.py); this router decides WHICH replica
serves a follower/bounded read.  Uniform random spreads load but keeps
hammering a slow replica at full weight; least-loaded needs global
state.  Power-of-two-choices is the classic middle: sample two
replicas, send to the one with the lower OBSERVED p99 — load-dependent
enough to starve a degraded replica, stateless enough to stay one dict
probe per read (Mitzenmacher's "two choices" result; the paper's
read fan-out motivation).

Thread model: ``pick``/``observe`` run on gateway worker threads.  All
shared state is per-host ``_Lat`` cells in a dict — inserts use
``setdefault`` (GIL-atomic), observations are single-writer-ish ring
writes where a lost sample is harmless, and ``pick`` only reads.  No
locks on the read path (gateway-hot rule, gateway/routing.py).
"""
from __future__ import annotations

import random
from typing import Dict, Iterable, Optional, Sequence


class _Lat:
    """Per-replica latency reservoir -> amortized p99 estimate.

    A 128-sample ring; the p99 is recomputed every 32 observations
    (sorting 128 floats per READ would be pure overhead, per-32 keeps
    the estimate at most a blink stale).  Unobserved replicas report
    p99 = 0.0 so new/idle replicas get explored rather than shunned.
    """

    CAP = 128
    RECOMPUTE_EVERY = 32

    __slots__ = ("ring", "n", "idx", "p99", "_since")

    def __init__(self):
        self.ring = [0.0] * self.CAP
        self.n = 0
        self.idx = 0
        self.p99 = 0.0
        self._since = 0

    def observe(self, seconds: float) -> None:
        self.ring[self.idx] = seconds
        self.idx = (self.idx + 1) % self.CAP
        if self.n < self.CAP:
            self.n += 1
        self._since += 1
        if self._since >= self.RECOMPUTE_EVERY:
            self._since = 0
            live = sorted(self.ring[: self.n])
            self.p99 = live[min(self.n - 1, int(0.99 * self.n))]


class ReadRouter:
    """Pick a serving replica host for follower/bounded reads.

    ``pick(hosts)`` is power-of-two-choices on per-host observed p99;
    ``observe(host, seconds)`` feeds each read's measured latency back.
    ``penalize(host)`` records a failure as a worst-case observation so
    a dark replica loses the next few coin flips without any explicit
    liveness plumbing (the breaker in gateway/rpc.py handles true
    darkness; this only biases selection away meanwhile)."""

    PENALTY_S = 5.0  # one failed read weighs like a 5s response

    __slots__ = ("_lat", "_rng")

    def __init__(self, seed: int = 0xD0B0A7):
        self._lat: Dict[str, _Lat] = {}
        # own Random instance: the router must not perturb (or be
        # perturbed by) global random state, and a fixed default seed
        # keeps single-threaded tests deterministic
        self._rng = random.Random(seed)

    # -- feedback ----------------------------------------------------
    def observe(self, host: str, seconds: float) -> None:
        cell = self._lat.get(host)
        if cell is None:
            cell = self._lat.setdefault(host, _Lat())
        cell.observe(seconds)

    def penalize(self, host: str) -> None:
        self.observe(host, self.PENALTY_S)

    def p99(self, host: str) -> float:
        cell = self._lat.get(host)
        return cell.p99 if cell is not None else 0.0

    # -- selection ----------------------------------------------------
    def pick(
        self,
        hosts: Sequence[str],
        exclude: Optional[Iterable[str]] = None,
    ) -> Optional[str]:
        """Two-choice pick over ``hosts`` (minus ``exclude``); None when
        no candidate remains.  One candidate short-circuits; two or
        more sample two DISTINCT indices and keep the lower p99."""
        if exclude:
            ex = set(exclude)
            hosts = [h for h in hosts if h not in ex]
        n = len(hosts)
        if n == 0:
            return None
        if n == 1:
            return hosts[0]
        rng = self._rng
        i = rng.randrange(n)
        j = rng.randrange(n - 1)
        if j >= i:
            j += 1
        a, b = hosts[i], hosts[j]
        return a if self.p99(a) <= self.p99(b) else b

    def snapshot(self) -> Dict[str, float]:
        """{host: observed p99 seconds} for stats/ledger surfaces."""
        return {h: c.p99 for h, c in self._lat.items()}
