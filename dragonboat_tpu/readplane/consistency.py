"""Consistency levels and read-result stamps for the read plane.

The level names follow the dragonboat/etcd read taxonomy (ReadIndex /
lease read) extended with the two replica-served contracts
(docs/READPLANE.md).  Everything here is plain data — the protocol
work lives in raft/node/nodehost; the routing in .router and gateway/.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

from ..request import RequestError


class Consistency(enum.IntEnum):
    """What the caller is promised about the value read.

    The int values double as the RPC consistency byte's LEVEL space —
    but note the wire flags (transport.wire.RPC_READ_*) are a separate
    enumeration that also carries the legacy lease/index/stale split;
    gateway/rpc.py maps between the two."""

    LINEARIZABLE = 0
    FOLLOWER_LINEARIZABLE = 1
    BOUNDED_STALENESS = 2


# canonical read-path labels (metrics `gateway_read_total{path=...}`,
# NodeHost.read_path_counts, scenario ledger columns)
PATH_LEASE = "lease"
PATH_READ_INDEX = "read_index"
PATH_FOLLOWER = "follower"
PATH_BOUNDED = "bounded"
READ_PATHS = (PATH_LEASE, PATH_READ_INDEX, PATH_FOLLOWER, PATH_BOUNDED)

# default staleness bound for BOUNDED_STALENESS, in ticks of the
# serving replica's logical clock.  50 ticks = 5 election windows at
# the test-default election_rtt=10: generous enough that a healthy
# follower (heartbeat every tick or two) never sheds, tight enough
# that a partitioned one sheds within one reroute interval.
BOUND_TICKS_DEFAULT = 50

# `readplane_staleness_ticks` histogram bucket bounds (ticks are
# integers; the metrics.Histogram default bounds are sub-second floats
# and would bucket every observation into +Inf)
STALENESS_TICK_BOUNDS = (0, 1, 2, 5, 10, 20, 50, 100, 200, 500)


class StaleBoundExceeded(RequestError):
    """BOUNDED_STALENESS shed: the serving replica cannot stamp the
    read within the caller's bound (leaderless, out of leader contact
    past the bound, or applied behind the leader's last-known commit).
    Retry elsewhere or escalate the consistency level."""


class ReadUnsupported(RequestError):
    """The remote server predates the readplane consistency byte (it
    answered ``unknown read mode``): degrade to a leader read."""


@dataclass
class ReadResult:
    """A read value plus its provenance stamp.

    ``path`` is one of READ_PATHS.  ``applied_index`` is the serving
    replica's applied index at lookup time (0 when the path does not
    stamp it).  ``staleness_ticks`` is the serving replica's ticks
    since last leader contact for BOUNDED_STALENESS (0 on the
    linearizable paths — they are, by contract, not stale).  ``host``
    is the serving host's raft address when routed by the gateway
    ("" for local NodeHost calls)."""

    value: object
    path: str
    applied_index: int = 0
    staleness_ticks: int = 0
    host: str = ""
