"""Read plane: replica read fan-out with explicit consistency levels.

The write plane funnels every proposal through the leader row; a
read-heavy "millions of users" profile must NOT funnel every query the
same way (ROADMAP item 2, read half).  This package names the three
read contracts the stack serves and routes them to the right replica:

* ``LINEARIZABLE`` — leader only: the CheckQuorum lease fast path with
  the ReadIndex quorum round as fallback (docs/GATEWAY.md).
* ``FOLLOWER_LINEARIZABLE`` — any voting replica: the follower issues
  the ReadIndex confirmation round to the leader (the raft layer
  forwards via the ``from_ != self`` path), waits ``applied >= index``
  and serves from its LOCAL state machine.  Linearizable, leader does
  one message round but zero state-machine work.
* ``BOUNDED_STALENESS`` — any replica, immediately: served from the
  local state machine, stamped with the replica's applied index and
  its staleness in ticks since last leader contact; SHED when the
  stamp would exceed the caller's bound.

Safety arguments and the consistency-level contract: docs/READPLANE.md.
Routing (replica sets + power-of-two-choices on observed per-replica
p99) lives in :mod:`.router`; the gateway wires it to the gossip-fed
collector view.
"""
from .consistency import (
    BOUND_TICKS_DEFAULT,
    Consistency,
    PATH_BOUNDED,
    PATH_FOLLOWER,
    PATH_LEASE,
    PATH_READ_INDEX,
    READ_PATHS,
    ReadResult,
    ReadUnsupported,
    STALENESS_TICK_BOUNDS,
    StaleBoundExceeded,
)
from .router import ReadRouter

__all__ = [
    "BOUND_TICKS_DEFAULT",
    "Consistency",
    "PATH_BOUNDED",
    "PATH_FOLLOWER",
    "PATH_LEASE",
    "PATH_READ_INDEX",
    "READ_PATHS",
    "ReadResult",
    "ReadRouter",
    "ReadUnsupported",
    "STALENESS_TICK_BOUNDS",
    "StaleBoundExceeded",
]
