"""The day's ledger: per-phase measurements + the per-fault-class
recovery/dip table, serializable as JSON and printable as a table
(``scripts/day_soak.sh`` prints it; tests assert on the dict form).

Every number is a measured delta over one phase's wall window, sampled
from the planes' own counters (gateway stats, transport stream totals,
nemesis stats, :data:`dragonboat_tpu.faults.RECOVERY_STATS`) — the
report never keeps its own timers, so "throughput dip per fault class"
reads from the same sources the operators' dashboards would.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class DayReport:
    """The outcome of one :class:`~.runner.ScenarioRunner` run."""

    seed: int = 0
    gear: str = "mini"
    plan: str = ""
    wall_s: float = 0.0
    phases: List[dict] = field(default_factory=list)
    baseline_committed_per_s: float = 0.0
    #: fault_class -> committed/s during that class's phase relative to
    #: the warmup baseline (1.0 = no dip; smaller = throughput dip)
    fault_dips: Dict[str, float] = field(default_factory=dict)
    #: RECOVERY_STATS.snapshot() at day end (count/worst/p99/margins
    #: per fault class)
    recovery: Dict[str, dict] = field(default_factory=dict)
    audit: Dict[str, object] = field(default_factory=dict)
    #: the plan's disturbance classes — ok requires EVERY one of these
    #: to have fired, not just the ones that happened to be recorded
    #: (the standard gears plan all five DISTURBANCE_CLASSES)
    classes_planned: List[str] = field(default_factory=list)
    disturbances_fired: Dict[str, int] = field(default_factory=dict)
    violations: List[str] = field(default_factory=list)
    aborted: str = ""
    timeline: str = ""
    #: device-path counters from the colocated fleet member's engine
    #: group (empty when the day ran host-only)
    colocated: Dict[str, int] = field(default_factory=dict)
    #: SLO burn-rate rows from the fleet scope (obs/slo.py) — the
    #: day's objective ledger with burning windows attributed to the
    #: collector marks (kill windows, phase boundaries) inside them.
    #: Carried, not gating: ``ok`` stays the recovery/audit verdict.
    slo: List[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (
            not self.aborted
            and not self.violations
            and bool(self.audit.get("ok", False))
            and all(
                self.disturbances_fired.get(c, 0) > 0
                for c in self.classes_planned
            )
            and all(
                r.get("violations", 0) == 0 for r in self.recovery.values()
            )
        )

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "seed": self.seed,
            "gear": self.gear,
            "wall_s": round(self.wall_s, 3),
            "baseline_committed_per_s": round(
                self.baseline_committed_per_s, 2
            ),
            "phases": self.phases,
            "fault_dips": {
                k: round(v, 4) for k, v in sorted(self.fault_dips.items())
            },
            "recovery": self.recovery,
            "audit": self.audit,
            "classes_planned": list(self.classes_planned),
            "disturbances_fired": self.disturbances_fired,
            "violations": self.violations,
            "aborted": self.aborted,
            "plan": self.plan,
            "colocated": dict(self.colocated),
            "slo": list(self.slo),
        }

    def to_json(self, path: str = "") -> str:
        text = json.dumps(self.to_dict(), indent=2, sort_keys=True)
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text

    def format_table(self) -> str:
        """The operator-facing ledger table (phases + the per-class
        recovery/dip summary)."""
        cols = (
            "phase", "class", "wall_s", "comm/s", "shed/s", "p99_ms",
            "lease%", "resumes",
        )
        rows = [cols]
        for p in self.phases:
            rows.append((
                p["name"],
                p.get("fault_class", "") or "-",
                f"{p['wall_s']:.1f}",
                f"{p['committed_per_s']:.0f}",
                f"{p['shed_per_s']:.0f}",
                f"{p['p99_s'] * 1000:.0f}",
                f"{p['lease_share'] * 100:.0f}",
                str(p.get("stream_resumes", 0)),
            ))
        widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
        lines = [
            "  ".join(c.ljust(widths[i]) for i, c in enumerate(r))
            for r in rows
        ]
        lines.append("")
        lines.append("fault class         dip    recoveries  worst_s  "
                     "p99_s  min_margin_s")
        for cls in sorted(set(self.fault_dips) | set(self.recovery)):
            r = self.recovery.get(cls, {})
            dip = self.fault_dips.get(cls)
            dip_s = "-" if dip is None else f"{dip:.2f}"
            lines.append(
                f"{cls:<18}  {dip_s:>5}"
                f"  {r.get('count', 0):>10}  {r.get('worst_s', 0.0):>7}"
                f"  {r.get('p99_s', 0.0):>5}  {r.get('min_margin_s', 0.0)}"
            )
        if self.slo:
            lines.append("")
            lines.append("objective             burn   bad/good      "
                         "burning-windows")
            for r in self.slo:
                lines.append(
                    f"{r['objective']:<20}  {r['burn_rate']:>5}"
                    f"  {r['bad']:.0f}/{r['good']:.0f}"
                    f"{'':<6}  {len(r.get('windows', ()))}"
                )
        verdict = "OK" if self.ok else (
            f"ABORTED in {self.aborted}" if self.aborted else "VIOLATIONS"
        )
        lines.append("")
        lines.append(
            f"day[{self.gear}] seed={self.seed} wall={self.wall_s:.1f}s "
            f"baseline={self.baseline_committed_per_s:.0f}/s "
            f"audit={'green' if self.audit.get('ok') else 'RED'} "
            f"-> {verdict}"
        )
        return "\n".join(lines)
