"""Child process for the multi-process production-day fleet.

One OS process == one NodeHost over real TCP + gossip on loopback,
fronted by a :class:`~dragonboat_tpu.gateway.rpc.RpcServer` — the
externally-observable deployment shape (docs/SCENARIO.md
"Multi-process gear").  Unlike ``tests/multiproc_runner.py``'s file
protocol, ALL client traffic arrives over the RPC ingress: the parent
drives commits, reads, session registration and even the nemesis
(``RPC_OP_FAULT`` is enabled — this worker exists to be shaken) through
the same wire a production client would use.  ``kill -9`` therefore
looks exactly like a machine crash from both sides: no shared memory,
no atexit, the parent's pending RPCs fail per the degradation matrix
and recovery is WAL replay + gossip re-resolution + raft catch-up.

Usage::

    python -m dragonboat_tpu.scenario.procworker <idx> <n> <workdir> \
        <base_port>

Port layout (loopback): raft = base+idx, gossip = base+100+idx,
RPC = base+200+idx — fixed per slot so a restarted worker is reachable
at the same RPC address (the parent's RemoteHostHandle reconnects
through its breaker without re-registration).

The worker writes ``ready-<idx>.json`` ({nhid, rpc, raft, gossip, pid})
once serving, then runs until ``stop-<idx>`` appears (graceful close,
for teardown) or it is killed outright (the interesting path).
"""
import json
import os
import sys
import time


def _write_atomic(path: str, obj) -> None:
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def main() -> None:
    idx = int(sys.argv[1])
    n = int(sys.argv[2])
    workdir = sys.argv[3]
    base_port = int(sys.argv[4])
    # this image's sitecustomize imports jax at interpreter start; pin
    # the cpu backend so a child never probes the TPU tunnel (the host
    # engine path used here needs no device at all)
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — no jax needed on this path
        pass

    from dragonboat_tpu import (
        Config,
        EngineConfig,
        ExpertConfig,
        GossipConfig,
        NodeHost,
        NodeHostConfig,
    )
    from dragonboat_tpu.audit.model import AuditKV
    from dragonboat_tpu.faults import FaultController
    from dragonboat_tpu.gateway.rpc import RpcServer
    from dragonboat_tpu.transport.tcp import tcp_transport_factory

    raft_addr = f"127.0.0.1:{base_port + idx}"
    gossip_addr = f"127.0.0.1:{base_port + 100 + idx}"
    rpc_addr = f"127.0.0.1:{base_port + 200 + idx}"
    # fleet-scope observability: tracing + flight recorder ON by
    # default so the parent's FleetScope has something to poll over
    # RPC_OP_OBS; DRAGONBOAT_PROC_OBS=0 runs the worker dark (the
    # degrade-matrix shape where recorder_tail answers enabled=False)
    obs_on = bool(int(os.environ.get("DRAGONBOAT_PROC_OBS", "1")))
    nh = NodeHost(
        NodeHostConfig(
            nodehost_dir=f"{workdir}/nh-{idx}",
            rtt_millisecond=20,
            raft_address=raft_addr,
            address_by_nodehost_id=True,
            enable_tracing=obs_on,
            trace_sample_rate=1.0,
            enable_flight_recorder=obs_on,
            gossip=GossipConfig(
                bind_address=gossip_addr,
                # every worker seeds at slot 1's gossip port; the
                # parent's observer joins through the same seed
                seed=[f"127.0.0.1:{base_port + 100 + 1}"],
            ),
            expert=ExpertConfig(
                engine=EngineConfig(exec_shards=1, apply_shards=1),
                transport_factory=tcp_transport_factory,
            ),
        )
    )
    # publish our nodehost id, then wait for the full member map:
    # gossip addressing resolves replica -> nodehost-id -> address
    # dynamically (a restarted peer is re-found wherever it binds)
    _write_atomic(f"{workdir}/nhid-{idx}.json", {"nhid": nh.nodehost_id})
    members = {}
    deadline = time.time() + 60
    while len(members) < n:
        for r in range(1, n + 1):
            p = f"{workdir}/nhid-{r}.json"
            if r not in members and os.path.exists(p):
                try:
                    with open(p) as f:
                        members[r] = json.load(f)["nhid"]
                except (json.JSONDecodeError, KeyError):
                    pass
        if time.time() > deadline:
            raise TimeoutError(f"worker {idx}: member map incomplete")
        time.sleep(0.1)
    # DRAGONBOAT_PROC_SHARDS grows the worker to a multi-shard host
    # (shards 1..S, all AuditKV, replica ids == slot numbers) — the
    # read-plane bench spreads its 100k-session plane across them
    n_shards = int(os.environ.get("DRAGONBOAT_PROC_SHARDS", "1"))
    for sid in range(1, max(1, n_shards) + 1):
        nh.start_replica(
            members, False, AuditKV,
            Config(replica_id=idx, shard_id=sid, election_rtt=20,
                   heartbeat_rtt=2, pre_vote=True, check_quorum=True),
        )

    # the nemesis plane, remotely drivable: the parent injects
    # asym_drop/asym_delay/partition windows on THIS host's transport
    # through the same RPC ingress clients use
    ctl = FaultController(seed=1000 + idx)
    ctl.install_nodehost(f"w{idx}", nh)
    # DRAGONBOAT_PROC_RPC_INFLIGHT narrows the per-host admission door
    # (RpcServer sheds RPC_ERR_BUSY beyond it) — the read-plane bench
    # uses it to make per-replica serving capacity the explicit
    # bottleneck being scaled
    inflight = int(os.environ.get("DRAGONBOAT_PROC_RPC_INFLIGHT", "64"))
    srv = RpcServer(nh, rpc_addr, fault_controller=ctl,
                    allow_fault_ops=True, max_inflight=inflight)
    srv.start()
    _write_atomic(
        f"{workdir}/ready-{idx}.json",
        {"nhid": nh.nodehost_id, "rpc": srv.listen_address,
         "raft": raft_addr, "gossip": gossip_addr, "pid": os.getpid()},
    )

    stop_path = f"{workdir}/stop-{idx}"
    while not os.path.exists(stop_path):
        time.sleep(0.1)
    srv.close()
    nh.close()


if __name__ == "__main__":
    main()
