"""The production-day fleet: hosts, shards, planes, lifecycle.

One :class:`DayFleet` owns everything the scenario runner shakes:

* six in-proc NodeHosts — ``h1..h3`` core, ``h4`` witness-only, ``h5``
  a non-voting big-state laggard, ``h6`` an empty spare (the region-
  drain target);
* two shards — :data:`~.plan.SH_MEM` (in-memory AuditKV, the audited
  gateway-session shard and DR subject) and :data:`~.plan.SH_DISK`
  (on-disk ``OnDiskKV`` with 3 voters + 1 witness + 1 non-voting — the
  mixed on-disk/in-memory/witness fleet the survey's drummer scenarios
  run);
* the planes: ONE seeded nemesis (crash handlers + churn + recorders),
  a ``Balancer`` over the core+spare hosts, and a ``Gateway`` fronting
  all of them.

Kill/restart is whole-host and keeps every plane's membership in sync
(gateway host map, balancer registration, nemesis installs).  The
``_assign`` registry tracks which replicas each host must restart
with; after membership-changing maneuvers (drain, DR) the runner calls
:meth:`refresh_assignments` to re-derive it from live cluster
membership instead of trusting a stale map.
"""
from __future__ import annotations

import shutil
import threading
from typing import Dict, Optional, Tuple

from ..balance import Balancer
from ..audit import AuditKV
from ..config import Config, EngineConfig, ExpertConfig, NodeHostConfig
from ..faults import FaultController
from ..gateway import Gateway, GatewayConfig
from ..logger import get_logger
from ..nodehost import NodeHost
from .plan import SH_DISK, SH_MEM

_log = get_logger("scenario")

CORE = ("h1", "h2", "h3")
WITNESS = "h4"
LAGGARD = "h5"
SPARE = "h6"
SLOTS = CORE + (WITNESS, LAGGARD, SPARE)

WITNESS_RID = 4
LAGGARD_RID = 5

# the colocated fleet member: one CORE host whose replicas (both
# shards) step through a shared ColocatedEngineGroup — the product
# device path riding the SAME scheduled churn as everyone else
COLO_SLOT = "h2"
# small ring window (entry-cache depth 256/shard) — the geometry the
# colocated chaos suite pins, reused here so the jit cache is warm
COLO_GEOM = dict(capacity=16, P=5, W=8, M=8, E=4, O=32, budget=4)


class DayFleet:
    """See module docstring.  ``tag`` namespaces transport addresses
    and on-disk dirs so concurrent fleets (tests) never collide."""

    def __init__(self, seed: int = 0, *, tag: str = "day",
                 workdir: str = "/tmp", colocated: bool = False):
        self.seed = seed
        self.tag = tag
        self.workdir = workdir
        self.colocated = colocated
        # slot -> ColocatedEngineGroup, REUSED across that slot's
        # restarts (the chaos-tested path: state generations + WAL
        # replay re-attach the restarted replicas to the live group)
        self._colo_groups: Dict[str, object] = {}
        self.addrs: Dict[str, str] = {s: f"{tag}-{s}" for s in SLOTS}
        self.slots: Dict[str, str] = {a: s for s, a in self.addrs.items()}
        self.hosts: Dict[str, NodeHost] = {}
        self._dead: set = set()
        self._lock = threading.RLock()
        # addr -> {shard: (replica_id, kind)}; kind: voter|witness|nonvoting
        self._assign: Dict[str, Dict[int, Tuple[int, str]]] = {}
        # shard -> {rid: addr} voter map (restart initial_members)
        self._members: Dict[int, Dict[int, str]] = {}
        self.nemesis: Optional[FaultController] = None
        self.balancer: Optional[Balancer] = None
        self.gateway: Optional[Gateway] = None
        self._sla_seq = 0

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def _dir(self, slot: str) -> str:
        return f"{self.workdir}/nh-{self.tag}-{slot}"

    def _sm_root(self) -> str:
        return f"{self.workdir}/{self.tag}-sm"

    def sm_factory(self, shard_id: int, replica_id: int):
        """One factory for every shard (the balancer hands it to
        start_replica on move targets too)."""
        if shard_id == SH_DISK:
            from ..bigstate.ondisk import ondisk_kv_factory

            return ondisk_kv_factory(self._sm_root())(shard_id, replica_id)
        return AuditKV(shard_id, replica_id)

    def config_factory(self, shard_id: int, replica_id: int) -> Config:
        # election windows are WIDE for an in-proc fleet (100/150 ms):
        # six hosts + gateway + traffic + balancer share one box (in CI,
        # one core), and a 20 ms window flaps check-quorum under that
        # load — constant step-downs would churn leadership far beyond
        # what the day SCHEDULES, wedging snapshot sends mid-stream
        if shard_id == SH_DISK:
            return Config(
                replica_id=replica_id, shard_id=shard_id,
                election_rtt=30, heartbeat_rtt=3, check_quorum=True,
                is_witness=(replica_id == WITNESS_RID),
                is_non_voting=(replica_id == LAGGARD_RID),
            )
        return Config(
            replica_id=replica_id, shard_id=shard_id,
            election_rtt=20, heartbeat_rtt=2, check_quorum=True,
        )

    def _colo_group(self, slot: str):
        group = self._colo_groups.get(slot)
        if group is None:
            from ..ops.colocated import ColocatedEngineGroup

            group = ColocatedEngineGroup(**COLO_GEOM)
            self._colo_groups[slot] = group
        return group

    def _make_host(self, slot: str) -> NodeHost:
        # single-shard engine pools: six hosts run on one box, and the
        # day's realism comes from plane interleaving, not from intra-
        # host engine parallelism — fewer threads keep tick cadence
        # honest under the GIL
        expert = ExpertConfig(
            engine=EngineConfig(exec_shards=1, apply_shards=1)
        )
        if self.colocated and slot == COLO_SLOT:
            # the factory is NODEHOST-level: every replica this host
            # runs (both shards) steps on the shared device group
            expert = ExpertConfig(
                engine=EngineConfig(exec_shards=1, apply_shards=1),
                step_engine_factory=self._colo_group(slot).factory,
            )
        return NodeHost(
            NodeHostConfig(
                nodehost_dir=self._dir(slot),
                rtt_millisecond=5,
                raft_address=self.addrs[slot],
                enable_flight_recorder=True,
                expert=expert,
            )
        )

    def colo_stats(self) -> Dict[str, int]:
        """Device-path counters from the colocated member's group —
        device_rows_stepped proves the launch pipeline actually rode
        the device, divergence_halts must stay zero through churn."""
        out: Dict[str, int] = {}
        for group in self._colo_groups.values():
            core = group.core
            if core is None:
                continue
            for k, v in dict(core.stats).items():
                out[k] = out.get(k, 0) + int(v)
        return out

    def build(self) -> None:
        from ..transport.inproc import reset_inproc_network

        reset_inproc_network()
        for slot in SLOTS:
            shutil.rmtree(self._dir(slot), ignore_errors=True)
        shutil.rmtree(self._sm_root(), ignore_errors=True)
        self.nemesis = FaultController(seed=self.seed)
        self.nemesis.set_crash_handlers(self.kill, self.restart)
        for slot in SLOTS:
            addr = self.addrs[slot]
            self.hosts[addr] = self._make_host(slot)
            self.nemesis.install_nodehost(addr, self.hosts[addr])
        core_addrs = {i + 1: self.addrs[s] for i, s in enumerate(CORE)}
        self._members = {SH_MEM: dict(core_addrs), SH_DISK: dict(core_addrs)}
        self._assign = {a: {} for a in self.addrs.values()}
        for rid, addr in core_addrs.items():
            nh = self.hosts[addr]
            for shard in (SH_MEM, SH_DISK):
                nh.start_replica(
                    core_addrs, False, self.sm_factory,
                    self.config_factory(shard, rid),
                )
                self._assign[addr][shard] = (rid, "voter")
        for shard in (SH_MEM, SH_DISK):
            self.wait_for_leader(shard)
        # the mixed tail: witness + non-voting big-state laggard
        self._add_member(SH_DISK, WITNESS_RID, WITNESS, "witness")
        self._add_member(SH_DISK, LAGGARD_RID, LAGGARD, "nonvoting")
        self.balancer = Balancer(
            self.sm_factory,
            self.config_factory,
            hosts={
                self.addrs[s]: self.hosts[self.addrs[s]]
                for s in CORE + (SPARE,)
            },
            replication_factor=3,
            seed=self.seed,
            catchup_timeout=90.0,
        )
        self.nemesis.install_balancer(self.balancer)
        self.nemesis.install_churn(
            self.live_hosts,
            shards=(SH_MEM,),
            balancer=self.balancer,
            sla_ticks=15_000,
            sla_cmd=self.sla_cmd,
            sla_per_try=2.0,
        )
        self.gateway = Gateway(
            dict(self.hosts), GatewayConfig(workers=2, default_timeout=4.0)
        )
        # close the loop: the balancer's collector reads the gateway's
        # per-shard latency/shed evidence (ClusterView.load rows)
        self.balancer.attach_load_source(self.gateway.shard_load)

    def _add_member(self, shard: int, rid: int, slot: str, kind: str) -> None:
        from ..client import call_with_retry

        addr = self.addrs[slot]
        api = self.hosts[self._members[shard][1]]
        if kind == "witness":
            call_with_retry(
                lambda: api.sync_request_add_witness(
                    shard, rid, addr, timeout=2.0
                ),
                timeout=20.0,
            )
        else:
            call_with_retry(
                lambda: api.sync_request_add_non_voting(
                    shard, rid, addr, timeout=2.0
                ),
                timeout=20.0,
            )
        self.hosts[addr].start_replica(
            {}, True, self.sm_factory, self.config_factory(shard, rid)
        )
        self._assign[addr][shard] = (rid, kind)

    # ------------------------------------------------------------------
    # membership views
    # ------------------------------------------------------------------
    def live_hosts(self) -> Dict[str, NodeHost]:
        with self._lock:
            return {
                a: nh for a, nh in self.hosts.items()
                if a not in self._dead and not getattr(nh, "_closed", False)
            }

    def hosts_holding(self, shard: int) -> Dict[str, NodeHost]:
        return {
            a: nh for a, nh in self.live_hosts().items()
            if nh._nodes.get(shard) is not None
        }

    def leader_host(self, shard: int) -> Optional[NodeHost]:
        for nh in self.live_hosts().values():
            try:
                if nh.is_leader_of(shard):
                    return nh
            except Exception:  # noqa: BLE001 — host closing mid-probe
                continue
        return None

    def wait_for_leader(self, shard: int, timeout: float = 20.0) -> NodeHost:
        import time as _time

        deadline = _time.time() + timeout
        while _time.time() < deadline:
            nh = self.leader_host(shard)
            if nh is not None:
                return nh
            _time.sleep(0.02)
        raise AssertionError(f"no leader for shard {shard} within {timeout}s")

    def sla_cmd(self) -> bytes:
        """A unique commit-continuity probe for the churn plane's SLA
        checks (SH_MEM; the ``_sla`` key is outside every audited key
        prefix, so the probe traffic never perturbs the history)."""
        from ..audit import audit_set_cmd

        with self._lock:
            self._sla_seq += 1
            n = self._sla_seq
        return audit_set_cmd("_sla", f"s{n}")

    def sla_probe(self, shard: int) -> bytes:
        if shard == SH_DISK:
            from ..bigstate.ondisk import put_cmd

            with self._lock:
                self._sla_seq += 1
                n = self._sla_seq
            return put_cmd(b"_sla", b"s%d" % n)
        return self.sla_cmd()

    def refresh_assignments(self) -> None:
        """Re-derive ``_assign``/``_members`` from live cluster
        membership (after drain / DR rewrote it)."""
        with self._lock:
            for a in self._assign:
                self._assign[a] = {}
            for shard in (SH_MEM, SH_DISK):
                holders = self.hosts_holding(shard)
                m = None
                for nh in holders.values():
                    try:
                        m = nh.get_shard_membership(shard)
                        if m is not None and m.addresses:
                            break
                    except Exception:  # noqa: BLE001 — mid-restart
                        continue
                if m is None:
                    continue
                self._members[shard] = dict(m.addresses)
                for rid, addr in m.addresses.items():
                    if addr in self._assign:
                        self._assign[addr][shard] = (rid, "voter")
                for rid, addr in m.witnesses.items():
                    if addr in self._assign:
                        self._assign[addr][shard] = (rid, "witness")
                for rid, addr in m.non_votings.items():
                    if addr in self._assign:
                        self._assign[addr][shard] = (rid, "nonvoting")

    def set_member_map(self, shard: int, members: Dict[int, str],
                       kind: str = "voter") -> None:
        """Overwrite one shard's voter map (the DR cycle rewrites
        membership wholesale before replicas restart)."""
        with self._lock:
            self._members[shard] = dict(members)
            for a in self._assign:
                self._assign[a].pop(shard, None)
            for rid, addr in members.items():
                if addr in self._assign:
                    self._assign[addr][shard] = (rid, kind)

    # ------------------------------------------------------------------
    # whole-host lifecycle (crash handlers + rolling restarts)
    # ------------------------------------------------------------------
    def kill(self, addr: str) -> None:
        with self._lock:
            nh = self.hosts.get(addr)
            if nh is None or addr in self._dead:
                return
            self._dead.add(addr)
        if self.gateway is not None:
            try:
                self.gateway.remove_host(addr)
            except Exception:  # noqa: BLE001 — gateway may be closing
                pass
        if self.balancer is not None and addr in self.balancer.hosts:
            self.balancer.remove_host(addr)
        nh.close()

    def restart(self, addr: str) -> None:
        slot = self.slots[addr]
        nh = self._make_host(slot)
        with self._lock:
            self.hosts[addr] = nh
            assigns = dict(self._assign.get(addr, {}))
            members = {s: dict(m) for s, m in self._members.items()}
            was_balanced = (
                self.balancer is not None
                and (slot in CORE or slot == SPARE)
            )
        self.nemesis.install_nodehost(addr, nh)
        for shard, (rid, kind) in sorted(assigns.items()):
            cfg = self.config_factory(shard, rid)
            if kind == "voter":
                nh.start_replica(members[shard], False, self.sm_factory, cfg)
            else:
                # witness / non-voting replicas joined; persisted state
                # carries their membership, a join restart re-attaches
                nh.start_replica({}, True, self.sm_factory, cfg)
        if was_balanced:
            self.balancer.join(addr, nh)
        if self.gateway is not None:
            try:
                self.gateway.add_host(addr, nh)
            except Exception:  # noqa: BLE001 — gateway may be closing
                pass
        with self._lock:
            self._dead.discard(addr)

    # ------------------------------------------------------------------
    # teardown / observability
    # ------------------------------------------------------------------
    def dump_timeline(self) -> str:
        from ..obs import hosts_timeline

        try:
            return hosts_timeline(self.live_hosts().values())
        except Exception:  # noqa: BLE001 — best-effort dump
            return ""

    def stream_totals(self) -> Dict[str, int]:
        """Cumulative snapshot-stream counters over the LIVE transports
        (restarted hosts reset theirs — ledger deltas clamp at zero)."""
        out = {"stream_resumes": 0, "stream_chunks": 0, "stream_bytes": 0}
        for nh in self.live_hosts().values():
            try:
                m = nh.transport.metrics
            except Exception:  # noqa: BLE001 — host closing
                continue
            for k in out:
                out[k] += int(m.get(k, 0))
        return out

    def close(self) -> None:
        if self.nemesis is not None:
            try:
                self.nemesis.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                _log.exception("nemesis stop failed")
        if self.gateway is not None:
            try:
                self.gateway.close()
            except Exception:  # noqa: BLE001
                _log.exception("gateway close failed")
        if self.balancer is not None:
            try:
                self.balancer.stop()
            except Exception:  # noqa: BLE001
                pass
        for nh in list(self.hosts.values()):
            try:
                nh.close()
            except Exception:  # noqa: BLE001
                pass
        self.hosts.clear()
