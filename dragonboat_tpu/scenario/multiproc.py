"""Multi-process fleet harness: separate OS processes, TCP + gossip +
RPC only, zero shared memory (docs/SCENARIO.md "Multi-process gear").

:class:`ProcFleet` spawns ``scenario/procworker.py`` children, fronts
them with the ordinary :class:`~dragonboat_tpu.gateway.Gateway` over
:class:`~dragonboat_tpu.gateway.rpc.RemoteHostHandle` clients, joins
their gossip mesh as an observer and runs a
:class:`~dragonboat_tpu.gateway.rpc.RouteFeeder` so leader routing
converges with no in-proc tap.  The nemesis is REAL: ``kill()`` is
``SIGKILL`` on the worker's process, and the asymmetric wire faults
go over the RPC fault op to the victim's own FaultController.

Two entry points ride it:

* :func:`run_rpc_smoke` — the ~5s CI gate (scripts/rpc_smoke.sh): a
  2-process fleet commits over the wire, the leader's process is
  SIGKILLed mid-service, a restart over the same dirs recovers within
  ``assert_recovery_sla``, and post-recovery commits + reroutes pass.
* :func:`run_mini_multiproc_day` — the 3-process mini production day
  (``DRAGONBOAT_MULTIPROC=1`` tier-1 gear): open-loop audited traffic
  through the gateway, a real leader SIGKILL + restart, an asymmetric
  one-way drop injected and healed, routing reconvergence, and the
  Wing–Gong client-history audit over everything that happened.
"""
from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from ..audit.checker import check_linearizable, check_stale_reads
from ..audit.history import HistoryRecorder
from ..audit.model import audit_set_cmd
from ..faults import assert_recovery_sla, asym_pair
from ..gateway import Gateway, GatewayBusy, GatewayConfig
from ..gateway.rpc import RemoteHostHandle, RouteFeeder
from ..logger import get_logger
from ..obs import FleetScope, Tracer
from ..transport.gossip import GossipManager

_log = get_logger("scenario")

SHARD = 1


class _GatewayObs:
    """The PARENT process as a fleet-scope target: the gateway's own
    metrics registry plus the client tracer whose rpc:propose roots the
    cross-process stitches.  No flight recorder in the parent."""

    def __init__(self, gateway: Gateway, tracer: Optional[Tracer]):
        self.metrics = gateway.metrics
        self.tracer = tracer
        self.recorder = None
        self.host = "gateway"


class ProcFleet:
    """N procworker children + the client-side planes over them."""

    def __init__(self, n: int = 3, *, workdir: str = "/tmp/mpday",
                 base_port: int = 29650, fresh: bool = True,
                 shards: int = 1, rpc_inflight: int = 64):
        self.n = n
        self.workdir = workdir
        self.base_port = base_port
        self.shards = shards
        self.rpc_inflight = rpc_inflight
        self.procs: Dict[int, subprocess.Popen] = {}
        self.handles: Dict[str, RemoteHostHandle] = {}
        self.ready: Dict[int, dict] = {}
        self.gossip: Optional[GossipManager] = None
        self.gateway: Optional[Gateway] = None
        self.feeder: Optional[RouteFeeder] = None
        # fleet-scope telemetry: the client-side tracer rides every
        # handle (trace context on request frames) and the scope polls
        # every worker + the parent itself
        self.tracer: Optional[Tracer] = None
        self.scope: Optional[FleetScope] = None
        if fresh:
            shutil.rmtree(workdir, ignore_errors=True)
        os.makedirs(workdir, exist_ok=True)

    # -- worker lifecycle -------------------------------------------------
    def _spawn(self, idx: int) -> subprocess.Popen:
        # the child resolves the package by PYTHONPATH, not the parent's
        # cwd — drives launched from a scratch dir must still spawn
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["DRAGONBOAT_PROC_SHARDS"] = str(self.shards)
        env["DRAGONBOAT_PROC_RPC_INFLIGHT"] = str(self.rpc_inflight)
        return subprocess.Popen(
            [sys.executable, "-m", "dragonboat_tpu.scenario.procworker",
             str(idx), str(self.n), self.workdir, str(self.base_port)],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
            env=env,
        )

    def _wait_ready(self, idx: int, timeout: float = 90.0) -> dict:
        path = f"{self.workdir}/ready-{idx}.json"
        deadline = time.time() + timeout
        while True:
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        info = json.load(f)
                    if info.get("pid") == self.procs[idx].pid:
                        return info
                except (OSError, json.JSONDecodeError, KeyError):
                    pass
            if self.procs[idx].poll() is not None:
                raise RuntimeError(f"worker {idx} died during startup")
            if time.time() > deadline:
                raise TimeoutError(f"worker {idx} never became ready")
            time.sleep(0.1)

    def start(self) -> None:
        for idx in range(1, self.n + 1):
            self.procs[idx] = self._spawn(idx)
        for idx in range(1, self.n + 1):
            self.ready[idx] = self._wait_ready(idx)
        self.tracer = Tracer(host="gateway", sample_rate=1.0)
        for idx in range(1, self.n + 1):
            # keyed by the child's NodeHostID: with address_by_nodehost_id
            # the membership addresses (and hence the collector's
            # leader_host / the routing cache keys) ARE the nhids, and a
            # restart over the same dirs keeps the id — so the handle
            # registration survives kills
            self.handles[self._key(idx)] = RemoteHostHandle(
                self.ready[idx]["rpc"], rtt_millisecond=20,
                tracer=self.tracer,
            )
        # observer membership in the children's gossip mesh: liveness
        # for the RouteFeeder comes from DIRECT contact, exactly what a
        # cross-process balance plane would consume
        self.gossip = GossipManager(
            nodehost_id=f"observer-{os.getpid()}",
            raft_address="observer",
            bind_address="127.0.0.1:0",
            seeds=[self.ready[i]["gossip"] for i in range(1, self.n + 1)],
            interval=0.1,
        )
        self.gossip.start()
        self.gateway = Gateway(
            dict(self.handles),
            GatewayConfig(workers=2, default_timeout=5.0,
                          cap_feedback=False),
        )
        self.feeder = RouteFeeder(self.gateway, self.gossip, interval=0.25)
        self.feeder.start()
        # the telemetry plane: one collector over every worker (polled
        # via RPC_OP_OBS) AND the parent gateway process (polled
        # in-proc) — the merged timeline crosses the process boundary
        self.scope = FleetScope()
        for idx in range(1, self.n + 1):
            self.scope.add_process(self._key(idx),
                                   self.handles[self._key(idx)])
        self.scope.add_process("gateway",
                               _GatewayObs(self.gateway, self.tracer))

    def _key(self, idx: int) -> str:
        return self.ready[idx]["nhid"]

    def raft_addr(self, idx: int) -> str:
        return self.ready[idx]["raft"]

    def handle(self, idx: int) -> RemoteHostHandle:
        return self.handles[self._key(idx)]

    def live_slots(self):
        return [i for i in range(1, self.n + 1)
                if self.procs[i].poll() is None]

    # -- nemesis ----------------------------------------------------------
    def kill(self, idx: int) -> None:
        """A true crash: SIGKILL the worker's OS process.  The handle
        stays registered — its breaker darkens it, and the fixed RPC
        port lets it reconnect after restart()."""
        p = self.procs[idx]
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)

    def restart(self, idx: int) -> None:
        """Respawn over the SAME dirs: WAL replay + gossip rejoin +
        raft catch-up, observed purely over the wire."""
        try:
            os.remove(f"{self.workdir}/ready-{idx}.json")
        except OSError:
            pass
        self.procs[idx] = self._spawn(idx)
        self.ready[idx] = self._wait_ready(idx)

    def leader_slot(self, timeout: float = 30.0) -> int:
        """The slot whose replica currently leads SHARD, asked over the
        wire (replica ids == slot numbers)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            for idx in self.live_slots():
                try:
                    lid, ok = self.handle(idx).get_leader_id(SHARD)
                except Exception:  # noqa: BLE001 — dark/restarting host
                    continue
                if ok and lid in self.procs:
                    return lid
            time.sleep(0.1)
        raise TimeoutError("no leader observed over RPC")

    def set_asym_drop(self, src: int, dst: int, p: float = 1.0) -> None:
        """One-way partition: src's sends to dst drop, dst->src flows.
        Installed on the SOURCE worker's FaultController (on_wire runs
        sender-side), driven over the RPC fault op."""
        self.handle(src).send_fault("activate", fault={
            "kind": "asym_drop",
            "targets": [asym_pair(self.raft_addr(src), self.raft_addr(dst))],
            "p": p,
        })

    def set_asym_delay(self, src: int, dst: int, delay: float,
                       p: float = 1.0) -> None:
        self.handle(src).send_fault("activate", fault={
            "kind": "asym_delay",
            "targets": [asym_pair(self.raft_addr(src), self.raft_addr(dst))],
            "p": p, "delay": delay,
        })

    def heal_wire(self, idx: int) -> None:
        self.handle(idx).send_fault("heal_wire")

    # -- teardown ---------------------------------------------------------
    def close(self) -> None:
        if self.scope is not None:
            self.scope.close()
        if self.feeder is not None:
            self.feeder.close()
        if self.gateway is not None:
            try:
                self.gateway.close()
            except Exception:  # noqa: BLE001 — dark remotes mid-close
                pass
        for h in self.handles.values():
            h.close()
        if self.gossip is not None:
            self.gossip.close()
        for idx, p in self.procs.items():
            if p.poll() is None:
                with open(f"{self.workdir}/stop-{idx}", "w") as f:
                    f.write("stop")
        for p in self.procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


def _sla_hosts(fleet: ProcFleet) -> Dict[str, RemoteHostHandle]:
    """SLA convergence is judged over LIVE workers only: a SIGKILLed
    slot's handle raises on every probe and would read as 'never
    converged' long after the survivors agree."""
    return {fleet._key(i): fleet.handle(i) for i in fleet.live_slots()}


# ---------------------------------------------------------------------------
# the ~5s CI gate (scripts/rpc_smoke.sh)
# ---------------------------------------------------------------------------
def run_rpc_smoke(n: int = 2, *, workdir: str = "/tmp/rpc-smoke",
                  base_port: int = 29750) -> dict:
    fleet = ProcFleet(n, workdir=workdir, base_port=base_port)
    out = {"committed": 0, "rerouted": False}
    try:
        fleet.start()
        gw = fleet.gateway

        # commits over the wire through the gateway (exactly-once)
        h = gw.connect(SHARD, timeout=30.0)
        for i in range(5):
            h.sync_propose(audit_set_cmd(f"pre{i}", str(i)), timeout=10.0)
            out["committed"] += 1
        assert gw.read(SHARD, "pre0", timeout=10.0) == "0"

        # SIGKILL the leader's PROCESS mid-service
        victim = fleet.leader_slot()
        fleet.kill(victim)

        # with n=2 the shard has no quorum until the restart; bring the
        # victim back over the same dirs and require recovery (WAL
        # replay + gossip re-resolution + catch-up) inside the SLA
        fleet.restart(victim)
        assert_recovery_sla(
            _sla_hosts(fleet), SHARD, sla_ticks=4000,
            cmd=audit_set_cmd("sla", "probe"), rtt_ms=20,
            per_try_timeout=1.0, fault_class="proc_kill9",
        )

        # routing reconverges off gossip+stats with zero shared memory
        deadline = time.time() + 20
        while gw.routes.lookup(SHARD) is None and time.time() < deadline:
            time.sleep(0.1)
        out["rerouted"] = gw.routes.lookup(SHARD) is not None

        # post-recovery commits + read-your-write through the gateway
        for i in range(3):
            h.sync_propose(audit_set_cmd(f"post{i}", str(i)), timeout=10.0)
            out["committed"] += 1
        assert gw.read(SHARD, "post2", timeout=10.0) == "2"
        gw.close_handle(h)
        return out
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# the mini multi-process production day (DRAGONBOAT_MULTIPROC=1 gear)
# ---------------------------------------------------------------------------
class _Traffic:
    """Open-loop audited traffic over the gateway: exactly-once writers
    plus a linearizable reader, every outcome recorded for the offline
    Wing–Gong audit (the scenario runner's traffic idiom, client-side
    only — no in-proc journal exists across process boundaries)."""

    def __init__(self, gw: Gateway, rec: HistoryRecorder, writers: int = 2):
        self._gw = gw
        self.rec = rec
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._writer_main, args=(w,),
                             daemon=True, name=f"mpday-writer-{w}")
            for w in range(writers)
        ] + [
            threading.Thread(target=self._reader_main, daemon=True,
                             name="mpday-reader")
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=20.0)

    def _writer_main(self, w: int) -> None:
        client = self.rec.new_client()
        handle = None
        seq = 0
        while not self._stop.is_set():
            if handle is None:
                try:
                    handle = self._gw.connect(SHARD, timeout=5.0)
                except Exception:  # noqa: BLE001 — fleet mid-outage
                    self._stop.wait(0.25)
                    continue
            key = f"w{w}-k{seq % 4}"
            val = f"{w}:{seq}"
            seq += 1
            op = self.rec.invoke(client, "w", key, val)
            try:
                handle.sync_propose(audit_set_cmd(key, val), timeout=2.5)
                self.rec.ok(op)
            except GatewayBusy:
                # shed at the door: definitely not in the history
                self.rec.fail(op)
            except Exception:  # noqa: BLE001 — maybe committed
                self.rec.ambiguous(op)
            self._stop.wait(0.02)

    def _reader_main(self) -> None:
        client = self.rec.new_client()
        seq = 0
        while not self._stop.is_set():
            key = f"w{seq % 2}-k{seq % 4}"
            seq += 1
            op = self.rec.invoke(client, "r", key)
            try:
                val = self._gw.read(SHARD, key, timeout=2.0)
                self.rec.ok(op, output=val)
            except Exception:  # noqa: BLE001 — reads are idempotent
                self.rec.fail(op)
            self._stop.wait(0.03)


def _mp_proc_kill(fleet: ProcFleet, phase, report: dict) -> None:
    """Real whole-host kill: SIGKILL the leader's process, require
    recovery inside the SLA, restart over the same dirs and wait until
    the victim answers stats over RPC again (catch-up observed from
    the outside)."""
    sla_ticks = int(phase.param("sla_ticks", 4000))
    victim = fleet.leader_slot()
    if fleet.scope is not None:
        # the kill window lands on the merged timeline AND the poll
        # window the SLO evaluator attributes the burn to
        fleet.scope.mark("proc_kill", f"slot={victim} (leader)")
    fleet.kill(victim)
    t0 = time.monotonic()
    assert_recovery_sla(
        _sla_hosts(fleet), SHARD, sla_ticks=sla_ticks,
        cmd=audit_set_cmd("sla-kill", "probe"), rtt_ms=20,
        per_try_timeout=1.0, fault_class=phase.fault_class,
    )
    report["sla"][phase.fault_class] = round(time.monotonic() - t0, 3)
    fleet.restart(victim)
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if fleet.handle(victim).balance_shard_stats():
                break
        except Exception:  # noqa: BLE001 — still replaying/joining
            pass
        time.sleep(0.2)
    if fleet.scope is not None:
        fleet.scope.mark("proc_restart", f"slot={victim}")


def _mp_asym_partition(fleet: ProcFleet, phase, report: dict) -> None:
    """Directional wire fault between real processes: the leader's
    sends toward one follower vanish (or crawl) while the reverse
    direction flows — the half-open link, held for the plan's window,
    then healed with the recovery SLA asserted after the heal.  The
    victims (who leads, which follower is struck) are runtime-sampled;
    the plan pins only kind/p/window."""
    kind = str(phase.param("kind", "asym_drop"))
    p = float(phase.param("p", 1.0))
    window = float(phase.param("window", 1.5))
    sla_ticks = int(phase.param("sla_ticks", 4000))
    leader = fleet.leader_slot()
    follower = next(i for i in fleet.live_slots() if i != leader)
    if kind == "asym_delay":
        fleet.set_asym_delay(
            leader, follower, float(phase.param("delay", 0.2)), p=p
        )
    else:
        fleet.set_asym_drop(leader, follower, p=p)
    time.sleep(window)  # let the one-way window bite under traffic
    fleet.heal_wire(leader)
    t0 = time.monotonic()
    assert_recovery_sla(
        _sla_hosts(fleet), SHARD, sla_ticks=sla_ticks,
        cmd=audit_set_cmd("sla-asym", "probe"), rtt_ms=20,
        per_try_timeout=1.0, fault_class=kind,
    )
    report["sla"][kind] = round(time.monotonic() - t0, 3)
    # routing reconverges purely off gossip + stats
    gw = fleet.gateway
    deadline = time.time() + 20
    while gw.routes.lookup(SHARD) is None and time.time() < deadline:
        time.sleep(0.1)
    assert gw.routes.lookup(SHARD) is not None, "route never reconverged"


def run_mini_multiproc_day(n: int = 3, *, workdir: str = "/tmp/mpday",
                           base_port: int = 29650, seed: int = 11) -> dict:
    """The acceptance scenario, SCHEDULE-DRIVEN: execute the seeded
    :meth:`DayPlan.multiproc` phases over a real 3-process fleet under
    open-loop gateway traffic — a real leader SIGKILL, then an
    asymmetric one-way partition injected over the RPC fault op and
    healed, each recovery under ``assert_recovery_sla``, and the full
    client history through the Wing–Gong audit.  The plan is byte-
    stable per seed (``report["plan"]``); victims stay runtime-sampled
    exactly like the in-proc gears."""
    from .plan import DayPlan

    plan = DayPlan.multiproc(seed)
    fleet = ProcFleet(n, workdir=workdir, base_port=base_port)
    report = {
        "sla": {}, "ops": 0, "audit": "pending",
        "seed": seed, "plan": plan.describe(), "phases": [],
    }
    try:
        fleet.start()
        gw = fleet.gateway
        scope = fleet.scope
        scope.start_poller(0.25)
        rec = HistoryRecorder()
        traffic = _Traffic(gw, rec)
        traffic.start()
        for phase in plan.phases:
            scope.mark("phase", phase.name)
            if phase.action == "proc_kill":
                _mp_proc_kill(fleet, phase, report)
            elif phase.action == "asym_partition":
                _mp_asym_partition(fleet, phase, report)
            else:
                # warmup/cooldown: steady-state traffic windows around
                # the disturbances (the cooldown is the post-heal tail)
                time.sleep(max(0.5, phase.duration))
            report["phases"].append(phase.name)
        traffic.stop()

        # -- the audit: full client history, Wing–Gong ------------------
        ops = rec.ops()
        report["ops"] = len(ops)
        lin = check_linearizable(ops)
        assert lin.ok, lin.describe()
        stale = check_stale_reads(ops)
        assert not stale, "\n".join(v.describe() for v in stale)
        report["audit"] = "ok"
        report["counts"] = rec.counts()

        # -- the telemetry verdict: gap, stitches, burn-rate ledger -----
        scope.close()
        scope.poll()  # final sweep so post-cooldown deltas land
        timeline = scope.merged_timeline()
        kinds = {e[3] for e in timeline}
        assert "obs_gap" in kinds, "kill window left no gap on the timeline"
        assert "proc_kill" in kinds
        stitches = scope.cross_process_stitches()
        assert stitches >= 1, "no cross-process trace stitched"
        slo_rows = scope.slo_report()
        assert slo_rows, "empty SLO report"
        report["slo"] = slo_rows
        report["obs"] = {
            "stitches": stitches,
            "polls": scope.polls,
            "reply_bytes": scope.reply_bytes,
            "procs": scope.proc_report(),
        }
        return report
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# the ~5s telemetry CI gate (scripts/fleetobs_smoke.sh)
# ---------------------------------------------------------------------------
def run_fleetobs_smoke(n: int = 2, *, workdir: str = "/tmp/fleetobs-smoke",
                       base_port: int = 29850) -> dict:
    """Fleet-scope smoke: a 2-process fleet takes gateway proposals
    carrying trace context, the scope polls every process over
    ``RPC_OP_OBS``, and the gate asserts at least one proposal's trace
    stitched across the RPC boundary plus a JSON-parseable SLO report
    with the full objective catalog."""
    fleet = ProcFleet(n, workdir=workdir, base_port=base_port)
    try:
        fleet.start()
        gw = fleet.gateway
        scope = fleet.scope
        h = gw.connect(SHARD, timeout=30.0)
        for i in range(8):
            h.sync_propose(audit_set_cmd(f"obs{i}", str(i)), timeout=10.0)
        assert gw.read(SHARD, "obs0", timeout=10.0) == "0"
        gw.close_handle(h)
        # spans end server-side on apply completion; two polls with a
        # short settle pick up the full request->raft->apply chains
        scope.poll()
        time.sleep(0.3)
        scope.poll()
        stitches = scope.cross_process_stitches()
        assert stitches >= 1, (
            f"no cross-process stitch:\n{scope.dump(SHARD)}"
        )
        rows = scope.slo_report()
        json.dumps(rows)  # the report must be a plain-JSON ledger
        assert {r["objective"] for r in rows} >= {
            "commit_p99", "shed_ratio"}, rows
        return {
            "stitches": stitches,
            "polls": scope.polls,
            "reply_bytes": scope.reply_bytes,
            "slo_objectives": len(rows),
            "burning": [r["objective"] for r in rows if r["burning"]],
        }
    finally:
        fleet.close()
