"""Production-day scenario orchestrator (docs/SCENARIO.md).

The fusing plane: every subsystem the survey names — device-backed
NodeHosts, the serving gateway, the balance control plane, big-state
storage with resumable capped streams, DR export/import, the seeded
nemesis, and the Wing–Gong audit — run TOGETHER through one
deterministic, seeded day schedule:

* :class:`DayPlan` / :class:`Phase` — the declarative schedule
  (``FaultPlan``-style byte-canonical ``describe()``); gears:
  :meth:`DayPlan.mini` (tier-1, ~30-60 s) and :meth:`DayPlan.full`
  (``DRAGONBOAT_SOAK_DAY=1``, hours);
* :class:`DayFleet` — the mixed fleet: on-disk big-state shards next
  to in-memory shards, a witness (dummy snapshots) and a non-voting
  big-state laggard, fronted by a Gateway, balanced by a Balancer,
  shaken by ONE seeded nemesis;
* :class:`ScenarioRunner` — executes the plan under live traffic,
  wraps every recovery in ``assert_recovery_sla(fault_class=...)``,
  records the whole client history for the offline audit, aborts on
  any SLA miss with a flight-recorder timeline;
* :class:`DayReport` — the per-phase ledger + per-fault-class
  recovery/dip table (JSON + printable).

The multi-process gear (:mod:`.multiproc`) lifts the same shape across
OS-process boundaries: :class:`ProcFleet` runs each host as a separate
process behind the RPC/TCP ingress with real gossip liveness, so the
nemesis's whole-host kill is a true ``SIGKILL``.
"""
from .fleet import CORE, LAGGARD, SPARE, WITNESS, DayFleet
from .multiproc import (
    ProcFleet,
    run_fleetobs_smoke,
    run_mini_multiproc_day,
    run_rpc_smoke,
)
from .plan import DISTURBANCE_CLASSES, DayPlan, Phase, SH_DISK, SH_MEM
from .report import DayReport
from .runner import ScenarioRunner

__all__ = [
    "CORE",
    "DISTURBANCE_CLASSES",
    "DayFleet",
    "DayPlan",
    "DayReport",
    "LAGGARD",
    "Phase",
    "ProcFleet",
    "SH_DISK",
    "SH_MEM",
    "SPARE",
    "ScenarioRunner",
    "WITNESS",
    "run_fleetobs_smoke",
    "run_mini_multiproc_day",
    "run_rpc_smoke",
]
