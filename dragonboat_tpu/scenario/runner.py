"""The production-day orchestrator: execute a :class:`~.plan.DayPlan`
over a :class:`~.fleet.DayFleet` under live gateway traffic.

The runner is the monitoring loop of the drummer heritage: it fires
each phase's nemesis sub-plan and maneuver, wraps EVERY recovery in
``assert_recovery_sla`` (labelled with the phase's fault class, so the
per-class dip/recovery table reads straight out of
:data:`dragonboat_tpu.faults.RECOVERY_STATS`), keeps open-loop session
traffic flowing through the Gateway the whole time, records the entire
client-observed history — both shards, one recorder — for the offline
Wing–Gong audit, and emits a :class:`~.report.DayReport` ledger.  A
failed SLA ABORTS the day: remaining phases are skipped and the merged
flight-recorder timeline is captured into the report (the post-incident
artifact a failed production day must leave behind).

DR boundary discipline: writes to the exported shard are FENCED (the
traffic plane parks its writers and drains in-flight ops) before the
export is cut, exactly like a production runbook would — an ack issued
after the export point would be silently rolled back by the import,
which is real data loss, not an audit artifact.  Reads keep flowing
through the outage (they fail cleanly) and the post-import reads join
the SAME history, so the checker's verdict spans the boundary.
"""
from __future__ import annotations

import os
import threading
import time
from random import Random
from typing import Dict, List, Optional

from .. import settings
from ..audit import (
    AuditReport,
    audit_set_cmd,
    check_bounded_reads,
    check_linearizable,
    check_sessions,
    check_stale_reads,
    settle_journals,
)
from ..audit.history import HistoryRecorder
from ..faults import (
    RECOVERY_STATS,
    Fault,
    FaultPlan,
    RecoverySLAViolation,
    STREAM_DST_PREFIX,
    assert_recovery_sla,
)
from ..gateway import GatewayBusy
from ..logger import get_logger
from ..obs import FleetScope, record_all
from ..readplane import Consistency
from .fleet import CORE, LAGGARD, SPARE, WITNESS, DayFleet
from .plan import SH_DISK, SH_MEM, DayPlan, Phase
from .report import DayReport

_log = get_logger("scenario")


class _Traffic:
    """Open-loop gateway traffic with full history recording.

    Per shard: writer threads (exactly-once handles on the audited
    in-memory shard, an at-most-once handle on the big-state shard) and
    one never-pausing reader thread — reads must straddle every outage
    so the history witnesses the failure AND the recovery."""

    def __init__(self, fleet: DayFleet, rec: HistoryRecorder,
                 pace: float = 0.012):
        self.fleet = fleet
        self.rec = rec
        self.pace = pace
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._paused: Dict[int, threading.Event] = {
            SH_MEM: threading.Event(), SH_DISK: threading.Event(),
        }
        self._parked: List[tuple] = []  # (shard, Event)
        self._handles: List = []

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        gw = self.fleet.gateway
        for i in range(2):
            h = gw.connect(SH_MEM, timeout=10.0)
            self._handles.append(h)
            self._spawn(SH_MEM, f"mem-w{i}", self._writer_mem, h)
        self._handles.append(gw.noop_handle(SH_DISK))
        self._spawn(SH_DISK, "disk-w0", self._writer_disk,
                    self._handles[-1])
        for shard, name in ((SH_MEM, "mem-r"), (SH_DISK, "disk-r")):
            t = threading.Thread(
                target=self._reader, args=(shard,),
                daemon=True, name=f"tpu-day-{name}",
            )
            self._threads.append(t)
            t.start()

    def _spawn(self, shard: int, name: str, fn, handle) -> None:
        parked = threading.Event()
        self._parked.append((shard, parked))
        t = threading.Thread(
            target=fn, args=(handle, self.rec.new_client(), parked),
            daemon=True, name=f"tpu-day-{name}",
        )
        self._threads.append(t)
        t.start()

    def pause_writers(self, shard: int, timeout: float = 15.0) -> bool:
        """Fence writes to one shard: park its writers and wait until
        every in-flight op has settled (the DR export precondition)."""
        self._paused[shard].set()
        deadline = time.monotonic() + timeout
        ok = True
        for s, parked in self._parked:
            if s != shard:
                continue
            ok = parked.wait(max(0.0, deadline - time.monotonic())) and ok
        return ok

    def resume_writers(self, shard: int) -> None:
        for s, parked in self._parked:
            if s == shard:
                parked.clear()
        self._paused[shard].clear()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=15.0)
        for h in self._handles:
            try:
                h.close(timeout=1.0)
            except Exception:  # noqa: BLE001 — gateway may be closed
                pass

    # -- drivers --------------------------------------------------------
    def _park_if_paused(self, shard: int, parked: threading.Event) -> bool:
        if not self._paused[shard].is_set():
            # a writer that raced resume_writers (observed the pause,
            # was preempted, then set its marker after the resume
            # cleared it) clears the stale marker HERE, before starting
            # another op — so a set parked flag always means the writer
            # is really parked and the next DR fence cannot pass with
            # an op in flight (review finding)
            if parked.is_set():
                parked.clear()
            return False
        parked.set()
        time.sleep(0.02)
        return True

    def _writer_mem(self, handle, cid: int, parked) -> None:
        rng = Random(7_000 + cid)
        seq = 0
        while not self._stop.is_set():
            if self._park_if_paused(SH_MEM, parked):
                continue
            seq += 1
            key = f"m:k{rng.randrange(24)}"
            val = f"{cid}:{seq}"
            op = self.rec.invoke(cid, "w", key, val)
            try:
                handle.sync_propose(audit_set_cmd(key, val), timeout=2.5)
                self.rec.ok(op)
            except GatewayBusy:
                self.rec.fail(op)  # shed at the door: definitely not in
            except Exception:  # noqa: BLE001 — timeout/terminated/closed
                self.rec.ambiguous(op)
            time.sleep(self.pace)

    def _writer_disk(self, handle, cid: int, parked) -> None:
        from ..bigstate.ondisk import put_cmd

        rng = Random(8_000 + cid)
        seq = 0
        while not self._stop.is_set():
            if self._park_if_paused(SH_DISK, parked):
                continue
            seq += 1
            key = f"d:k{rng.randrange(8)}"
            val = f"{cid}:{seq}"
            op = self.rec.invoke(cid, "w", key, val)
            try:
                handle.sync_propose(
                    put_cmd(key.encode(), val.encode()), timeout=2.5
                )
                self.rec.ok(op)
            except GatewayBusy:
                self.rec.fail(op)
            except Exception:  # noqa: BLE001 — at-most-once: maybe in
                self.rec.ambiguous(op)
            time.sleep(2 * self.pace)

    def _reader(self, shard: int) -> None:
        rng = Random(9_000 + shard)
        cid = self.rec.new_client()
        gw = self.fleet.gateway
        while not self._stop.is_set():
            if shard == SH_MEM:
                key = f"m:k{rng.randrange(24)}"
                query = key
            else:
                key = f"d:k{rng.randrange(8)}"
                query = key.encode()
            op = self.rec.invoke(cid, "r", key)
            try:
                v = gw.read(shard, query, timeout=2.0)
                if isinstance(v, bytes):
                    v = v.decode()
                self.rec.ok(op, output=v)
            except Exception:  # noqa: BLE001 — outage reads fail clean
                self.rec.fail(op)
            time.sleep(2 * self.pace)


class _SlotObs:
    """Fleet-scope target for one in-proc day slot, resolved at POLL
    time: a rolling restart replaces ``fleet.hosts[addr]`` with a new
    NodeHost, and the scope's epoch check reads the new incarnation's
    rings from their start without re-registration."""

    def __init__(self, fleet: DayFleet, addr: str):
        self._fleet = fleet
        self._addr = addr
        self.host = addr

    def _nh(self):
        return self._fleet.hosts.get(self._addr)

    def raft_address(self) -> str:
        return self._addr

    @property
    def metrics(self):
        return getattr(self._nh(), "metrics", None)

    @property
    def recorder(self):
        return getattr(self._nh(), "recorder", None)

    @property
    def tracer(self):
        return getattr(self._nh(), "tracer", None)

    @property
    def nodehost_id(self):
        return getattr(self._nh(), "nodehost_id", "")

    @property
    def uptime_s(self):
        return getattr(self._nh(), "uptime_s", None)


class _GatewayObs:
    """The day's gateway as a fleet-scope target (its own registry
    carries the request histogram + shed counters the SLO catalog
    selects)."""

    def __init__(self, fleet: DayFleet):
        self._fleet = fleet
        self.host = "gateway"
        self.recorder = None
        self.tracer = None

    @property
    def metrics(self):
        return getattr(self._fleet.gateway, "metrics", None)


class ScenarioRunner:
    """Execute one :class:`DayPlan`; see module docstring."""

    def __init__(
        self,
        plan: DayPlan,
        *,
        tag: str = "day",
        workdir: str = "/tmp",
        sla_ticks: int = 15_000,
        traffic_pace: float = 0.012,
        colocated: bool = False,
    ):
        self.plan = plan
        self.fleet = DayFleet(
            plan.seed, tag=tag, workdir=workdir, colocated=colocated
        )
        self.sla_ticks = sla_ticks
        self.traffic_pace = traffic_pace
        self.rec = HistoryRecorder()
        self.report = DayReport(
            seed=plan.seed, gear=plan.gear, plan=plan.describe(),
            classes_planned=list(plan.classes_planned()),
        )
        self._dr_epoch = 0
        self._probe_cid: Optional[int] = None
        # the day's telemetry plane: polled at phase boundaries, its
        # burn-rate rows land on report.slo (docs/OBSERVABILITY.md)
        self.scope: Optional[FleetScope] = None

    # ------------------------------------------------------------------
    def run(self) -> DayReport:
        saved = (
            settings.Soft.snapshot_chunk_size,
            settings.Soft.snapshot_stream_max_tries,
        )
        # small chunks + a wide retry budget: kill windows must leave
        # room to RESUME, not exhaust the stream job (docs/BIGSTATE.md)
        settings.Soft.snapshot_chunk_size = 128 * 1024
        settings.Soft.snapshot_stream_max_tries = 10
        RECOVERY_STATS.reset()
        t_day = time.monotonic()
        traffic = None
        try:
            self.fleet.build()
            self.scope = FleetScope()
            for addr in list(self.fleet.hosts):
                self.scope.add_process(addr, _SlotObs(self.fleet, addr))
            self.scope.add_process("gateway", _GatewayObs(self.fleet))
            self.scope.poll()  # baseline window: warmup deltas start here
            self._probe_cid = self.rec.new_client()
            traffic = _Traffic(self.fleet, self.rec, pace=self.traffic_pace)
            traffic.start()
            self._traffic = traffic
            for phase in self.plan.phases:
                # ANY phase failure aborts the day through the same
                # path: an SLA miss and an unexpected maneuver error
                # (wedged wait_for_leader, membership race) must both
                # leave the ledger + timeline artifact a failed
                # production day owes its operator (review finding —
                # a bare traceback with no report is not a verdict)
                try:
                    self._run_phase(phase)
                except RecoverySLAViolation as e:
                    self.report.aborted = phase.name
                    self.report.violations.append(
                        f"{phase.name}: {e}"
                    )
                    self.report.timeline = (
                        getattr(e, "timeline", "")
                        or self.fleet.dump_timeline()
                    )
                    _log.error(
                        "day ABORTED in phase %s: %s", phase.name, e
                    )
                    break
                except Exception as e:  # noqa: BLE001 — see above
                    self.report.aborted = phase.name
                    self.report.violations.append(
                        f"{phase.name}: unexpected {type(e).__name__}: {e}"
                    )
                    self.report.timeline = self.fleet.dump_timeline()
                    _log.exception(
                        "day ABORTED in phase %s (unexpected)", phase.name
                    )
                    break
            traffic.stop()
            traffic = None
            nemesis = self.fleet.nemesis
            if nemesis is not None:
                self.report.violations.extend(nemesis.churn_violations)
            self.report.colocated = self.fleet.colo_stats()
            if self.report.colocated:
                # the colocated member's own invariants, phrased as day
                # verdicts: the launch pipeline must actually STEP on
                # the device path (a day that silently fell back to the
                # host engine proves nothing), and scheduled churn must
                # never trip a divergence fail-stop (I5).  With one
                # colocated slot its two replicas are the only group
                # members, so rows-stepped — not intra-group routing —
                # is the device-path evidence.
                if not self.report.colocated.get("device_rows_stepped", 0):
                    self.report.violations.append(
                        "colocated member never stepped on the device "
                        f"path: {self.report.colocated}"
                    )
                if self.report.colocated.get("divergence_halts", 0):
                    self.report.violations.append(
                        "colocated member divergence fail-stop under "
                        f"scheduled churn: {self.report.colocated}"
                    )
            if not self.report.aborted:
                self._final_audit()
        finally:
            if traffic is not None:
                traffic.stop()
            self.fleet.close()
            (
                settings.Soft.snapshot_chunk_size,
                settings.Soft.snapshot_stream_max_tries,
            ) = saved
        self.report.recovery = RECOVERY_STATS.snapshot()
        self.report.wall_s = time.monotonic() - t_day
        self._dip_table()
        if self.scope is not None:
            self.report.slo = self.scope.slo_report()
        return self.report

    # ------------------------------------------------------------------
    # phase machinery
    # ------------------------------------------------------------------
    def _sample(self) -> Dict[str, float]:
        gw = self.fleet.gateway.stats()
        streams = self.fleet.stream_totals()
        nstats = dict(self.fleet.nemesis.stats)
        return {
            "committed": gw["committed"],
            "shed": gw["shed"],
            "lease": gw["lease_reads"],
            "fallback": gw["read_fallbacks"],
            "p99_s": gw["p99_s"],
            "stream_resumes": streams["stream_resumes"],
            "stream_kills": nstats.get("stream_kills", 0),
            "stream_stalls": nstats.get("stream_stalled", 0),
            "churn": sum(
                nstats.get(k, 0)
                for k in (
                    "churn_leader_kills", "churn_leader_transfers",
                    "churn_member_adds", "churn_balance_moves",
                )
            ),
        }

    def _run_phase(self, phase: Phase) -> None:
        record_all(
            self.fleet.live_hosts(), 0, "day:phase", phase.name
        )
        if self.scope is not None:
            self.scope.mark("phase", phase.name)
        t0 = time.monotonic()
        s0 = self._sample()
        extras: Dict[str, object] = {}
        if phase.faults:
            # a churn fault activated while the target shard is mid-
            # election SKIPS (no leader to strike); phases fire from a
            # settled cluster so every scheduled disturbance lands
            for shard in sorted({
                t for f in phase.faults for t in f.targets
                if isinstance(t, int)
            }):
                self.fleet.wait_for_leader(shard)
            done = self.fleet.nemesis.run_phase(
                FaultPlan(list(phase.faults)),
                timeout=max(60.0, 4 * phase.duration + 60.0),
            )
            if not done:
                raise RecoverySLAViolation(
                    f"nemesis sub-plan of {phase.name} did not complete"
                )
        if phase.action:
            extras = self._do_action(phase)
        floor = t0 + phase.duration
        while time.monotonic() < floor:
            time.sleep(0.05)
        s1 = self._sample()
        wall = max(1e-6, time.monotonic() - t0)
        lease_d = max(0, s1["lease"] - s0["lease"])
        fall_d = max(0, s1["fallback"] - s0["fallback"])
        ledger = {
            "name": phase.name,
            "fault_class": phase.fault_class,
            "wall_s": round(wall, 3),
            "committed": max(0, s1["committed"] - s0["committed"]),
            "committed_per_s": round(
                max(0, s1["committed"] - s0["committed"]) / wall, 2
            ),
            "shed_per_s": round(
                max(0, s1["shed"] - s0["shed"]) / wall, 2
            ),
            "p99_s": round(s1["p99_s"], 4),
            "lease_share": round(
                lease_d / (lease_d + fall_d), 4
            ) if (lease_d + fall_d) else 0.0,
            "stream_resumes": max(
                0, s1["stream_resumes"] - s0["stream_resumes"]
            ),
            "stream_kills": max(
                0, s1["stream_kills"] - s0["stream_kills"]
            ),
        }
        ledger.update(extras)
        if phase.faults and "events" not in extras:
            # faults-only phases (leader churn): executed churn events,
            # straight off the nemesis counters — a schedule whose every
            # event SKIPPED must not read as a day that churned
            ledger["events"] = max(0, s1["churn"] - s0["churn"])
        self.report.phases.append(ledger)
        if phase.name == "warmup":
            self.report.baseline_committed_per_s = ledger["committed_per_s"]
        if phase.fault_class:
            fired = ledger.get("events", 1)
            self.report.disturbances_fired[phase.fault_class] = (
                self.report.disturbances_fired.get(phase.fault_class, 0)
                + int(fired)
            )
        record_all(
            self.fleet.live_hosts(), 0, "day:phase-end", phase.name
        )
        if self.scope is not None:
            # one poll window per phase: the SLO evaluator's burn rows
            # attribute straight to phase boundaries
            self.scope.poll()

    def _do_action(self, phase: Phase) -> Dict[str, object]:
        a = phase.action
        if a == "rolling_restart":
            return self._rolling_restart(phase)
        if a == "catchup_chaos":
            return self._catchup_chaos(phase)
        if a == "drain":
            return self._drain(phase)
        if a == "dr_cycle":
            return self._dr_cycle(phase)
        if a == "read_hot":
            return self._read_hot(phase)
        if a == "write_hot":
            return self._write_hot(phase)
        if a == "diurnal":
            return self._diurnal(phase)
        if a == "elastic":
            return self._elastic(phase)
        raise ValueError(f"unknown phase action {a!r}")

    def _sla(self, shard: int, fault_class: str) -> None:
        assert_recovery_sla(
            self.fleet.hosts_holding(shard),
            shard,
            sla_ticks=self.sla_ticks,
            cmd=self.fleet.sla_probe(shard),
            per_try_timeout=2.0,
            fault_class=fault_class,
        )

    # -- maneuvers ------------------------------------------------------
    def _rolling_restart(self, phase: Phase) -> Dict[str, object]:
        n = int(phase.param("hosts", len(CORE)))
        grace = float(phase.param("grace", 0.5))
        restarted = 0
        for slot in CORE[:n]:
            addr = self.fleet.addrs[slot]
            held = sorted(self.fleet._assign.get(addr, {}))
            self.fleet.kill(addr)
            time.sleep(grace)
            self.fleet.restart(addr)
            restarted += 1
            for shard in held:
                self._sla(shard, "rolling_restart")
        return {"events": restarted}

    def _catchup_chaos(self, phase: Phase) -> Dict[str, object]:
        payload_mb = int(phase.param("payload_mb", 2))
        cap = int(phase.param("cap_mb", 4)) * 1024 * 1024
        kill_p = float(phase.param("kill_p", 0.4))
        stall_p = float(phase.param("stall_p", 0.3))
        stall_delay = float(phase.param("stall_delay", 0.01))
        fleet, ctl = self.fleet, self.fleet.nemesis
        lag_addr = fleet.addrs[LAGGARD]
        wit_addr = fleet.addrs[WITNESS]
        fleet.kill(lag_addr)
        fleet.kill(wit_addr)
        # the laggard misses a payload the leader then compacts away:
        # its catch-up MUST be a snapshot stream, the witness's a dummy
        chunk = b"\xa5" * (512 * 1024)
        n_cmds = payload_mb * 2
        last_key = b"_big%d" % (n_cmds - 1)
        from ..bigstate.ondisk import put_cmd
        from ..client import propose_with_retry

        nh = fleet.wait_for_leader(SH_DISK)
        deadline = time.monotonic() + max(60.0, payload_mb * 2.0)
        for i in range(n_cmds):
            propose_with_retry(
                nh, nh.get_noop_session(SH_DISK),
                put_cmd(b"_big%d" % i, chunk),
                deadline=deadline, per_try_timeout=3.0,
            )
        for a, h in fleet.hosts_holding(SH_DISK).items():
            try:
                h.sync_request_snapshot(SH_DISK, compaction_overhead=1)
            except Exception:  # noqa: BLE001 — follower may decline
                pass
            h.set_snapshot_send_rate(cap)
        targets = (
            STREAM_DST_PREFIX + lag_addr, STREAM_DST_PREFIX + wit_addr,
        )
        kill = Fault("snapshot_stream_kill", targets=targets, p=kill_p)
        stall = Fault(
            "snapshot_stream_stall", targets=targets, p=stall_p,
            delay=stall_delay,
        )
        ctl.activate(kill)
        ctl.activate(stall)
        kills0 = ctl.stats.get("stream_kills", 0)
        try:
            fleet.restart(lag_addr)
            fleet.restart(wit_addr)
            lag = fleet.hosts[lag_addr]
            end = time.monotonic() + 90.0
            caught = False
            while time.monotonic() < end:
                if ctl.stats.get("stream_kills", 0) > kills0:
                    # one mid-transfer kill witnessed: heal the kill
                    # window so the RESUME (not endless retries) is
                    # what completes the transfer
                    ctl.deactivate(kill)
                try:
                    if lag.stale_read(SH_DISK, last_key) == chunk:
                        caught = True
                        break
                except Exception:  # noqa: BLE001 — replica mid-restore
                    pass
                time.sleep(0.05)
        finally:
            ctl.deactivate(kill)
            ctl.deactivate(stall)
            # the chaos-tier cap must not leak into later phases'
            # catch-ups (rolling restarts, balance moves) and skew
            # their ledger rows; 0 retires the cap entirely (the fleet
            # runs uncapped by default; drain sets its own before
            # moving and retires it after)
            for h in fleet.hosts_holding(SH_DISK).values():
                h.set_snapshot_send_rate(0)
        if not caught:
            raise RecoverySLAViolation(
                "big-state laggard never caught up under stream chaos: "
                f"stats={ctl.stats}"
            )
        self._sla(SH_DISK, "stream_chaos")
        return {
            "events": 1,
            "payload_mb": payload_mb,
            "kills": ctl.stats.get("stream_kills", 0) - kills0,
        }

    def _drain(self, phase: Phase) -> Dict[str, object]:
        fleet = self.fleet
        slot = str(phase.param("host", "h3"))
        addr = fleet.addrs[slot]
        to_slot = str(phase.param("to", SPARE))
        to_addr = fleet.addrs[to_slot]
        # the receiving region may itself have been drained by an
        # earlier cycle (the full gear alternates h3<->h6): re-join it
        # so the planner has somewhere to land the moves
        if to_addr in fleet.live_hosts():
            fleet.balancer.join(to_addr, fleet.hosts[to_addr])
        for h in fleet.hosts_holding(SH_DISK).values():
            h.set_snapshot_send_rate(8 * 1024 * 1024)
        try:
            rep = fleet.balancer.drain(
                addr, timeout=float(phase.param("timeout", 90.0))
            )
        except Exception as e:  # noqa: BLE001 — a drain that cannot
            # converge is a failed recovery, and the day must abort
            # through the same path as an SLA miss
            raise RecoverySLAViolation(f"region drain failed: {e!r}") from e
        fleet.refresh_assignments()
        for shard in (SH_MEM, SH_DISK):
            self._sla(shard, "drain")
        # retire the drain-tier cap so later phases' catch-ups run
        # uncapped again (same cross-phase leak as the chaos tier's)
        for h in fleet.hosts_holding(SH_DISK).values():
            h.set_snapshot_send_rate(0)
        catchup = {}
        last = fleet.balancer.last_move_report
        if isinstance(last, dict):
            catchup = dict(last.get("catchup") or {})
        return {
            "events": max(1, int(rep.get("executed", 0))),
            "drain_passes": rep.get("passes", 0),
            "drain_catchup": catchup,
        }

    def _dr_cycle(self, phase: Phase) -> Dict[str, object]:
        fleet = self.fleet
        shard = int(phase.param("shard", SH_MEM))
        self._dr_epoch += 1
        epoch = self._dr_epoch
        outdir = os.path.join(
            fleet.workdir, f"{fleet.tag}-dr-{epoch}"
        )
        cid = self._probe_cid
        # boundary reads BEFORE the export: the checker's verdict must
        # span the DR boundary inside one history
        pre_keys = ["m:k0", "m:k1"]
        for key in pre_keys:
            op = self.rec.invoke(cid, "r", key)
            try:
                self.rec.ok(
                    op, output=fleet.gateway.read(shard, key, timeout=3.0)
                )
            except Exception:  # noqa: BLE001
                self.rec.fail(op)
        # fence writes; an ack issued after the export point would be
        # rolled back by the import (module docstring)
        if not self._traffic.pause_writers(shard):
            raise RecoverySLAViolation(
                "DR write fence did not drain: an in-flight op could "
                "ack after the export point (lost-ack risk); aborting"
            )
        try:
            leader = fleet.wait_for_leader(shard)
            manifest = leader.export_snapshot(shard, outdir)
            holders = fleet.hosts_holding(shard)
            for nh in holders.values():
                nh.stop_shard(shard)
            slot_order = {s: i for i, s in enumerate(
                CORE + (WITNESS, LAGGARD, SPARE))}
            addrs = sorted(
                holders, key=lambda a: slot_order[fleet.slots[a]]
            )
            members = {
                10 * epoch + i + 1: a for i, a in enumerate(addrs)
            }
            for rid, a in members.items():
                nh = fleet.hosts[a]
                ss = nh.import_snapshot(outdir, shard, rid, members)
                if not ss.imported or ss.index != manifest.index:
                    raise RecoverySLAViolation(
                        f"DR import mismatch on {a}: {ss}"
                    )
                nh.start_replica(
                    members, False, fleet.sm_factory,
                    fleet.config_factory(shard, rid),
                )
            fleet.set_member_map(shard, members)
            self._sla(shard, "dr_cycle")
        finally:
            self._traffic.resume_writers(shard)
        # boundary reads AFTER the import, same history, same client
        for key in pre_keys:
            op = self.rec.invoke(cid, "r", key)
            try:
                self.rec.ok(
                    op, output=fleet.gateway.read(shard, key, timeout=5.0)
                )
            except Exception:  # noqa: BLE001
                self.rec.fail(op)
        return {"events": 1, "dr_index": manifest.index}

    def _read_hot(self, phase: Phase) -> Dict[str, object]:
        """The zipfian read storm (ROADMAP 5c, traffic shape): hot-key
        skewed readers hammer one shard through the gateway's read
        plane, split across FOLLOWER_LINEARIZABLE / BOUNDED_STALENESS /
        LINEARIZABLE (docs/READPLANE.md).  Follower reads join the
        Wing-Gong history as plain "r" ops — the offline audit, not
        this method, is the safety argument; bounded reads carry their
        stamp in ``op.value`` for check_bounded_reads.  The ledger row
        carries the observed read-path split; a storm that never
        reached a replica-served path is a failed phase, not a quiet
        row."""
        import bisect

        fleet = self.fleet
        gw = fleet.gateway
        shard = int(phase.param("shard", SH_MEM))
        n_keys = int(phase.param("keys", 24))
        skew = float(phase.param("skew", 1.2))
        readers = int(phase.param("readers", 3))
        bound = int(phase.param("bound_ticks", 100))
        burst = max(0.8, float(phase.duration))
        fleet.wait_for_leader(shard)
        # zipf CDF over key ranks: m:k0 is the hot key (same key space
        # the writers churn, so the storm joins a contended history)
        w = [1.0 / (r ** skew) for r in range(1, n_keys + 1)]
        tot = sum(w)
        cdf: List[float] = []
        acc = 0.0
        for x in w:
            acc += x / tot
            cdf.append(acc)
        rp0 = dict(gw.stats()["read_paths"])
        stop_at = time.monotonic() + burst
        hot_hits = [0] * readers

        def storm(idx: int) -> None:
            rng = Random(12_000 + idx)
            cid = self.rec.new_client()
            while time.monotonic() < stop_at:
                key = f"m:k{bisect.bisect_left(cdf, rng.random())}"
                if key == "m:k0":
                    hot_hits[idx] += 1
                roll = rng.random()
                if roll < 0.3:
                    op = self.rec.invoke(cid, "bounded", key)
                    try:
                        res = gw.read_at(
                            shard, key,
                            consistency=Consistency.BOUNDED_STALENESS,
                            timeout=2.0, bound_ticks=bound,
                        )
                        op.value = (
                            res.applied_index, res.staleness_ticks, bound
                        )
                        v = res.value
                        if isinstance(v, bytes):
                            v = v.decode()
                        self.rec.ok(op, output=v)
                    except Exception:  # noqa: BLE001 — shed/outage
                        self.rec.fail(op)
                    continue
                level = (
                    Consistency.FOLLOWER_LINEARIZABLE
                    if roll < 0.8 else Consistency.LINEARIZABLE
                )
                op = self.rec.invoke(cid, "r", key)
                try:
                    res = gw.read_at(
                        shard, key, consistency=level, timeout=2.0
                    )
                    v = res.value
                    if isinstance(v, bytes):
                        v = v.decode()
                    self.rec.ok(op, output=v)
                except Exception:  # noqa: BLE001 — reads fail clean
                    self.rec.fail(op)

        threads = [
            threading.Thread(
                target=storm, args=(i,), daemon=True,
                name=f"tpu-day-readhot-{i}",
            )
            for i in range(readers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=burst + 30.0)
        rp1 = gw.stats()["read_paths"]
        split = {
            k: max(0, rp1.get(k, 0) - rp0.get(k, 0)) for k in rp1
        }
        served = sum(
            split.get(p, 0)
            for p in ("lease", "read_index", "follower", "bounded")
        )
        if not (split.get("follower") and split.get("bounded")):
            raise RecoverySLAViolation(
                "read-hot storm never reached the replica read paths: "
                f"split={split}"
            )
        return {
            "events": 1,
            "reads": served,
            "read_paths": split,
            "hot_key_reads": sum(hot_hits),
        }

    @staticmethod
    def _zipf_cdf(n_keys: int, skew: float) -> List[float]:
        w = [1.0 / (r ** skew) for r in range(1, n_keys + 1)]
        tot = sum(w)
        cdf: List[float] = []
        acc = 0.0
        for x in w:
            acc += x / tot
            cdf.append(acc)
        return cdf

    def _storm_writers(self, shard: int, n: int, cdf: List[float],
                       stop_at, *, seed_base: int = 13_000,
                       pace_fn=None) -> Dict[str, int]:
        """Run ``n`` zipfian writer threads against the audited shard
        until ``stop_at`` (a float deadline or a callable returning
        True to stop).  Each writer owns an exactly-once gateway handle
        and records every op in the Wing–Gong history (ok / shed-fail /
        ambiguous, the _Traffic discipline).  ``pace_fn(t)`` returns
        the inter-write sleep at elapsed day-phase time ``t`` (None:
        unpaced — the storm shape)."""
        import bisect

        gw = self.fleet.gateway
        done = (stop_at if callable(stop_at)
                else (lambda: time.monotonic() >= stop_at))
        hot_hits = [0] * n
        wrote = [0] * n
        shed = [0] * n
        t0 = time.monotonic()

        def storm(idx: int) -> None:
            rng = Random(13_000 + idx if seed_base == 13_000
                         else seed_base + idx)
            cid = self.rec.new_client()
            try:
                h = gw.connect(shard, timeout=10.0)
            except Exception:  # noqa: BLE001 — storm starts mid-outage
                return
            seq = 0
            try:
                while not done():
                    r = bisect.bisect_left(cdf, rng.random())
                    key = f"m:k{r}"
                    if r == 0:
                        hot_hits[idx] += 1
                    seq += 1
                    val = f"{cid}:{seq}"
                    op = self.rec.invoke(cid, "w", key, val)
                    try:
                        h.sync_propose(audit_set_cmd(key, val), timeout=2.5)
                        self.rec.ok(op)
                        wrote[idx] += 1
                    except GatewayBusy:
                        self.rec.fail(op)  # shed at the door: not in
                        shed[idx] += 1
                    except Exception:  # noqa: BLE001 — maybe committed
                        self.rec.ambiguous(op)
                    if pace_fn is not None:
                        time.sleep(pace_fn(time.monotonic() - t0))
            finally:
                try:
                    h.close(timeout=1.0)
                except Exception:  # noqa: BLE001 — gateway closing
                    pass

        threads = [
            threading.Thread(
                target=storm, args=(i,), daemon=True,
                name=f"tpu-day-writehot-{i}",
            )
            for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        return {
            "writes": sum(wrote),
            "hot_key_writes": sum(hot_hits),
            "write_shed": sum(shed),
        }

    def _write_hot(self, phase: Phase) -> Dict[str, object]:
        """The write half of the zipfian storm (ROADMAP 5c, traffic
        shape): hot-key skewed writers hammer the audited shard's
        exactly-once path.  Every write joins the Wing–Gong history —
        the skew is adversarial precisely because dedupe + per-session
        ordering must hold while one key's apply order is contended."""
        fleet = self.fleet
        shard = int(phase.param("shard", SH_MEM))
        n_keys = int(phase.param("keys", 24))
        skew = float(phase.param("skew", 1.2))
        writers = int(phase.param("writers", 3))
        burst = max(0.8, float(phase.duration))
        fleet.wait_for_leader(shard)
        cdf = self._zipf_cdf(n_keys, skew)
        out = self._storm_writers(
            shard, writers, cdf, time.monotonic() + burst
        )
        if not out["writes"]:
            raise RecoverySLAViolation(
                f"write-hot storm landed zero commits: {out}"
            )
        return {"events": 1, **out}

    def _diurnal(self, phase: Phase) -> Dict[str, object]:
        """Sinusoidal offered-load swing (diurnal in miniature): writer
        pacing modulates by ``1 + amp*sin(2*pi*t/period)`` and the
        ledger row records the observed peak/trough committed rates —
        the serving plane must ride the swing without shedding at the
        trough's budget (no hard swing assert: the 1-core container
        flattens small swings; the row is the evidence)."""
        import math

        fleet = self.fleet
        gw = fleet.gateway
        shard = int(phase.param("shard", SH_MEM))
        writers = int(phase.param("writers", 3))
        period = max(0.2, float(phase.param("period", 1.0)))
        amp = min(0.95, max(0.0, float(phase.param("amp", 0.6))))
        burst = max(0.8, float(phase.duration))
        fleet.wait_for_leader(shard)
        base = 2 * self.traffic_pace

        def pace(t: float) -> float:
            # offered load ~ (1 + amp*sin): the gap is its reciprocal
            return base / max(0.05, 1.0 + amp * math.sin(
                2.0 * math.pi * t / period))

        cdf = self._zipf_cdf(int(phase.param("keys", 24)), 0.0)
        stop_at = time.monotonic() + burst
        rates: List[float] = []

        def sampler() -> None:
            dt = max(0.05, period / 8.0)
            prev = gw.stats()["committed"]
            while time.monotonic() < stop_at:
                time.sleep(dt)
                cur = gw.stats()["committed"]
                rates.append(max(0, cur - prev) / dt)
                prev = cur

        st = threading.Thread(target=sampler, daemon=True,
                              name="tpu-day-diurnal-sampler")
        st.start()
        out = self._storm_writers(
            shard, writers, cdf, stop_at, seed_base=14_000, pace_fn=pace
        )
        st.join(timeout=30.0)
        if not out["writes"]:
            raise RecoverySLAViolation(
                f"diurnal swing landed zero commits: {out}"
            )
        peak = round(max(rates), 2) if rates else 0.0
        trough = round(min(rates), 2) if rates else 0.0
        return {
            "events": 1,
            "writes": out["writes"],
            "peak_committed_per_s": peak,
            "trough_committed_per_s": trough,
            "swing": round(peak / trough, 2) if trough > 0 else 0.0,
        }

    def _elastic(self, phase: Phase) -> Dict[str, object]:
        """The elastic disturbance class (docs/BALANCE.md "Load-reactive
        rebalancing"): close the measurement->placement loop under a
        hostile write storm and PROVE the move shed the heat.

        Sequence: (1) quiet pre-check — with the phase's policy armed,
        run ``quiet_passes`` feedback passes under baseline traffic and
        require ZERO load-driven moves (the hysteresis guarantee, in
        the ledger as ``quiet_moves``); (2) manufacture genuine heat —
        transfer the big-state shard's leadership onto the audited
        shard's leader host, so both commit paths contend for that
        host's single engine worker; (3) zipfian write storm against
        the audited shard while the main loop samples the gateway's
        per-shard p99 and runs ``load_rebalance_once`` — the balancer
        must fire >=1 move; (4) keep the storm up through a tail so
        the post-move latency picture is measured UNDER the same
        offered load, and require the hot shard's p99 to drop below
        the storm peak; (5) recovery SLA around the whole maneuver,
        same as every other class."""
        from ..balance import LoadPolicy

        fleet = self.fleet
        gw = fleet.gateway
        bal = fleet.balancer
        shard = int(phase.param("shard", SH_MEM))
        n_keys = int(phase.param("keys", 24))
        skew = float(phase.param("skew", 1.4))
        writers = int(phase.param("writers", 4))
        hot_p99_ms = int(phase.param("hot_p99_ms", 60))
        hot_submit_floor = int(phase.param("hot_submit", 20))
        min_samples = int(phase.param("min_samples", 12))
        hysteresis = int(phase.param("hysteresis", 2))
        cooldown = int(phase.param("cooldown", 8))
        quiet_passes = int(phase.param("quiet_passes", 4))
        storm_s = max(1.0, float(phase.param("storm_s", 2.5)))
        pass_sleep = 0.12  # one cadence for quiet AND storm passes:
        # the submit trigger is a per-pass delta, so comparable windows
        # are what make the quiet/storm separation meaningful
        fleet.wait_for_leader(shard)
        fleet.wait_for_leader(SH_DISK)
        # thresholds are runtime-adaptive (like victim sampling, OUT of
        # describe()): the plan pins only the floors.  Submit-rate is
        # the PRIMARY trigger — offered load is what "load-reactive"
        # reacts to, and it separates storm from quiet far more
        # sharply than the absolute tail on a loaded 1-core box;
        # p99 stays as the secondary trigger with a 3x-baseline guard.
        base_row = gw.shard_load().get(shard) or {}
        base_p99 = float(base_row.get("p99_s", 0.0) or 0.0)
        sub0 = int(base_row.get("submitted", 0))
        time.sleep(0.6)
        sub1 = int((gw.shard_load().get(shard) or {}).get("submitted", 0))
        quiet_rate = max(0.0, (sub1 - sub0) / 0.6)
        hot_p99_s = max(hot_p99_ms / 1000.0, 3.0 * base_p99)
        hot_submit = max(
            hot_submit_floor, int(3.0 * quiet_rate * pass_sleep) + 1
        )
        bal.set_load_policy(LoadPolicy(
            hot_p99_s=hot_p99_s,
            hot_shed=8,
            hot_submit=hot_submit,
            min_samples=min_samples,
            hysteresis=hysteresis,
            cooldown=cooldown,
            max_moves=1,
        ))
        # (1) quiet pre-check: baseline traffic must fire ZERO moves
        quiet_moves = 0
        for _ in range(max(hysteresis + 1, quiet_passes)):
            rep = bal.load_rebalance_once()
            quiet_moves += rep["executed"] + rep["failed"]
            time.sleep(pass_sleep)
        if quiet_moves:
            raise RecoverySLAViolation(
                "elastic: quiet window fired load-driven moves "
                f"(hysteresis broken): {bal.last_load_report}"
            )
        # (2) colocate the two leaders: find the audited shard's leader
        # host and transfer the big-state shard's leadership onto it
        leader_nh = fleet.wait_for_leader(shard)
        hot_host = next(
            (a for a, h in fleet.hosts.items() if h is leader_nh), ""
        )
        colocated = False
        if hot_host:
            disk_ent = fleet._assign.get(hot_host, {}).get(SH_DISK)
            disk_rid = disk_ent[0] if disk_ent else None
            if disk_rid:
                disk_leader = fleet.wait_for_leader(SH_DISK)
                try:
                    disk_leader.request_leader_transfer(SH_DISK, disk_rid)
                    end = time.monotonic() + 5.0
                    while time.monotonic() < end:
                        if fleet.wait_for_leader(SH_DISK) is fleet.hosts[
                                hot_host]:
                            colocated = True
                            break
                        time.sleep(0.05)
                except Exception:  # noqa: BLE001 — the storm still
                    # heats the shard without the colocation boost
                    pass
        # (3) the storm + the feedback loop.  Alongside the zipfian
        # mem-shard storm, two disk-shard writers hammer the COLOCATED
        # big-state leader — the cross-shard engine contention is what
        # the move must escape.  Disk ops join the recorded history
        # (same d:k* key space as the baseline disk writer).
        from ..bigstate.ondisk import put_cmd

        state = {"stop": False}
        out_box: Dict[str, Dict[str, int]] = {}

        def run_storm() -> None:
            out_box["w"] = self._storm_writers(
                shard, writers, self._zipf_cdf(n_keys, skew),
                lambda: state["stop"], seed_base=15_000,
            )

        def disk_heat(idx: int) -> None:
            rng = Random(16_000 + idx)
            cid = self.rec.new_client()
            try:
                h = gw.connect(SH_DISK, timeout=10.0)
            except Exception:  # noqa: BLE001 — storm mid-outage
                return
            seq = 0
            try:
                while not state["stop"]:
                    key = f"d:k{rng.randrange(8)}"
                    seq += 1
                    val = f"{cid}:{seq}"
                    op = self.rec.invoke(cid, "w", key, val)
                    try:
                        h.sync_propose(
                            put_cmd(key.encode(), val.encode()),
                            timeout=2.5,
                        )
                        self.rec.ok(op)
                    except GatewayBusy:
                        self.rec.fail(op)
                    except Exception:  # noqa: BLE001 — maybe committed
                        self.rec.ambiguous(op)
            finally:
                try:
                    h.close(timeout=1.0)
                except Exception:  # noqa: BLE001 — gateway closing
                    pass

        storm_t = threading.Thread(target=run_storm, daemon=True,
                                   name="tpu-day-elastic-storm")
        heat_ts = [
            threading.Thread(target=disk_heat, args=(i,), daemon=True,
                             name=f"tpu-day-elastic-heat-{i}")
            for i in range(2)
        ]
        shed0 = int((gw.shard_load().get(shard) or {}).get("shed", 0))
        storm_t.start()
        for t in heat_ts:
            t.start()
        p99_peak = 0.0
        p99_after = 0.0
        executed = failed = 0
        moves: List[str] = []
        hard_cap = time.monotonic() + storm_s + 8.0
        try:
            # pre-move: sample heat + run the loop until a move fires
            while time.monotonic() < hard_cap:
                row = gw.shard_load().get(shard) or {}
                p99_peak = max(p99_peak, float(row.get("p99_s", 0.0)))
                rep = bal.load_rebalance_once()
                executed += rep["executed"]
                failed += rep["failed"]
                moves.extend(rep["moves"])
                if executed:
                    break
                time.sleep(pass_sleep)
            if not executed:
                raise RecoverySLAViolation(
                    "elastic: storm fired no load-driven move "
                    f"(p99_peak={p99_peak:.4f}s p99_thr={hot_p99_s:.4f}s "
                    f"submit_thr={hot_submit}/pass "
                    f"last={bal.last_load_report})"
                )
            # (4) post-move tail: same storm, fresh window — wait for
            # the per-shard budget to flush into the post-move picture
            tail_end = time.monotonic() + max(1.2, 0.6 * storm_s)
            tail_cap = time.monotonic() + max(4.0, storm_s)
            while time.monotonic() < tail_end:
                time.sleep(0.1)
            row = gw.shard_load().get(shard) or {}
            p99_after = float(row.get("p99_s", 0.0))
            while p99_after >= p99_peak and time.monotonic() < tail_cap:
                time.sleep(0.15)
                row = gw.shard_load().get(shard) or {}
                p99_after = float(row.get("p99_s", 0.0))
        finally:
            state["stop"] = True
            storm_t.join(timeout=60.0)
            for t in heat_ts:
                t.join(timeout=30.0)
        shed1 = int((gw.shard_load().get(shard) or {}).get("shed", 0))
        # (5) the same recovery gate every class gets
        self._sla(shard, "elastic")
        if p99_after >= p99_peak:
            raise RecoverySLAViolation(
                "elastic: move did not shed the hot shard's p99 "
                f"(storm peak {p99_peak:.4f}s -> after {p99_after:.4f}s, "
                f"moves={moves})"
            )
        return {
            "events": executed,
            "moves": moves,
            "moves_failed": failed,
            "colocated_leaders": colocated,
            "quiet_moves": quiet_moves,
            "p99_storm_s": round(p99_peak, 4),
            "p99_after_s": round(p99_after, 4),
            "shed_delta": max(0, shed1 - shed0),
            "writes": out_box.get("w", {}).get("writes", 0),
        }

    # ------------------------------------------------------------------
    # verdicts
    # ------------------------------------------------------------------
    def _final_audit(self) -> None:
        fleet = self.fleet
        ops = self.rec.ops()
        mem_ops = self.rec.ops_for("m:")
        sessions = None
        try:
            journals = settle_journals(
                fleet.hosts_holding(SH_MEM), SH_MEM, timeout=30.0
            )
            sessions = check_sessions(mem_ops, journals)
        except Exception as e:  # noqa: BLE001 — divergent journals ARE
            # an audit failure, not an infrastructure error
            self.report.violations.append(f"journal settle: {e!r}")
        rep = AuditReport(
            linearizability=check_linearizable(ops),
            stale=check_stale_reads(ops),
            sessions=sessions,
            bounded=check_bounded_reads(ops),
        )
        counts = self.rec.counts()
        self.report.audit = {
            "ok": bool(rep.ok) and not self.report.violations,
            "ops": counts,
            "keys_checked": rep.linearizability.keys_checked,
            "detail": "" if rep.ok else rep.describe(),
        }
        if not rep.ok:
            self.report.timeline = (
                self.report.timeline or fleet.dump_timeline()
            )

    def _dip_table(self) -> None:
        base = self.report.baseline_committed_per_s
        if base <= 0:
            return
        for p in self.report.phases:
            cls = p.get("fault_class")
            if not cls:
                continue
            dip = p["committed_per_s"] / base
            cur = self.report.fault_dips.get(cls)
            self.report.fault_dips[cls] = (
                dip if cur is None else min(cur, dip)
            )
