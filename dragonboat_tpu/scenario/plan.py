"""The production-day plan: a declarative, seeded phase schedule.

reference: the drummer/nemesis heritage (PAPER.md) — dragonboat's
credibility soak is scheduled churn plus a monitoring loop that keeps
repairing the fleet while traffic flows.  A :class:`DayPlan` is to the
scenario orchestrator what :class:`~dragonboat_tpu.faults.FaultPlan` is
to the nemesis: the complete, byte-canonical description of what will
be done to the cluster.  ``describe()`` is the determinism contract —
two plans built from the same seed and arguments are the SAME schedule
iff their describe() strings are byte-equal (tests/test_scenario.py
pins this), and every runtime-sampled victim (which host leads, which
stream a kill strikes) stays out of it by construction.

Two gears:

* :meth:`DayPlan.mini` — the tier-1-scale mini-day (~30-60 s, small
  fleet, every disturbance class fired at least once); ``scale < 1``
  shrinks it further for the ~10 s smoke gear.
* :meth:`DayPlan.full` — the env-gated hours-long day
  (``DRAGONBOAT_SOAK_DAY=1``, ``scripts/day_soak.sh``): repeated
  disturbance cycles sized to ``hours``, with the on-disk payload
  raised to GB scale when ``DRAGONBOAT_BIGSTATE_GB=1``
  (:func:`dragonboat_tpu.bigstate.gb_tier`).

The six disturbance classes (every gear fires each at least once):
``rolling_restart``, ``leader_churn``, ``stream_chaos``, ``drain``,
``dr_cycle``, ``elastic`` — see docs/SCENARIO.md for the class catalog
and the ledger each phase emits.  ``read_hot``, ``write_hot`` and
``diurnal`` are TRAFFIC-SHAPE phases, not disturbance classes
(ROADMAP 5c): the zipfian hot-key read/write storms against the
audited shard and the sinusoidal offered-load swing — their ledger
rows carry the observed split/swing.  ``elastic`` IS a class: it
drives a zipfian write storm and REQUIRES the balancer's
load-feedback loop to fire ≥1 move that sheds the hot shard's p99
(docs/BALANCE.md "Load-reactive rebalancing").

:meth:`DayPlan.multiproc` is the third gear (``DRAGONBOAT_MULTIPROC``):
a short schedule over the cross-process ProcFleet, the only gear whose
wire can express DIRECTIONAL faults — its ``asym_partition`` phase
fires the PR 16 ``asym_drop`` kinds.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from random import Random
from typing import List, Optional, Tuple

from ..faults import Fault

#: the scenario fleet's shard ids (fixed — the plan references them)
SH_MEM = 1   # in-memory AuditKV: audited gateway session traffic + DR
SH_DISK = 2  # on-disk OnDiskKV: big-state plane, witness + non-voting

#: the six disturbance classes a production day must fire
DISTURBANCE_CLASSES = (
    "rolling_restart",
    "leader_churn",
    "stream_chaos",
    "drain",
    "dr_cycle",
    "elastic",
)


@dataclass(frozen=True)
class Phase:
    """One phase of the day.

    ``action`` names an orchestrator maneuver the runner executes
    (``rolling_restart`` / ``catchup_chaos`` / ``drain`` / ``dr_cycle``
    / ``read_hot`` or empty for traffic-only phases); ``faults`` is a nemesis
    sub-plan executed via :meth:`FaultController.run_phase` before the
    action; ``duration`` is the minimum wall time of the phase (traffic
    keeps flowing until it elapses, so even a fast action yields a
    measurable throughput window).  ``params`` is a sorted key/value
    tuple — part of the byte-canonical describe()."""

    name: str
    fault_class: str = ""
    duration: float = 0.0
    action: str = ""
    params: Tuple[Tuple[str, object], ...] = ()
    faults: Tuple[Fault, ...] = ()

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default

    def describe(self) -> str:
        ps = ",".join(f"{k}={v!r}" for k, v in self.params)
        fs = ";".join(f.describe() for f in self.faults)
        return (
            f"phase {self.name} class={self.fault_class} "
            f"dur={self.duration:g} action={self.action} "
            f"params({ps}) faults[{fs}]"
        )


def _p(**kw) -> Tuple[Tuple[str, object], ...]:
    return tuple(sorted(kw.items()))


@dataclass
class DayPlan:
    """An ordered production-day schedule (see module docstring)."""

    seed: int
    gear: str
    phases: List[Phase] = field(default_factory=list)

    def describe(self) -> str:
        head = f"dayplan gear={self.gear} seed={self.seed}"
        return "\n".join([head] + [p.describe() for p in self.phases])

    def classes_planned(self) -> Tuple[str, ...]:
        return tuple(
            sorted({p.fault_class for p in self.phases if p.fault_class})
        )

    # ------------------------------------------------------------------
    # builders
    # ------------------------------------------------------------------
    @staticmethod
    def mini(seed: int, *, scale: float = 1.0) -> "DayPlan":
        """The tier-1 mini-day.  ``scale`` shrinks durations, payload
        and the restart sweep (the smoke gear uses ~0.4); every
        disturbance class still fires at least once at any scale."""
        rng = Random(seed)

        def j(lo: float, hi: float) -> float:
            # schedule jitter, rounded so describe() stays byte-stable
            return round(rng.uniform(lo, hi), 3)

        sc = max(0.2, float(scale))
        restarts = 3 if sc >= 0.75 else 1
        payload_mb = max(1, int(round(3 * sc)))
        phases = [
            Phase("warmup", duration=round(3.0 * sc, 3)),
            Phase(
                "rolling_restart",
                fault_class="rolling_restart",
                duration=round(1.0 * sc, 3),
                action="rolling_restart",
                params=_p(hosts=restarts, grace=j(0.3, 0.6)),
            ),
            Phase(
                "leader_churn",
                fault_class="leader_churn",
                duration=round(1.5 * sc, 3),
                action="",
                faults=(
                    Fault("leader_kill", at=j(0.1, 0.4),
                          duration=j(0.8, 1.4), targets=(SH_MEM,)),
                    Fault("leader_transfer", at=j(2.6, 3.2),
                          targets=(SH_MEM,)),
                ),
            ),
            Phase(
                "stream_chaos",
                fault_class="stream_chaos",
                duration=round(1.0 * sc, 3),
                action="catchup_chaos",
                params=_p(
                    payload_mb=payload_mb,
                    cap_mb=4,
                    kill_p=j(0.3, 0.5),
                    stall_p=j(0.2, 0.4),
                    stall_delay=j(0.005, 0.02),
                ),
            ),
            Phase(
                "drain",
                fault_class="drain",
                duration=round(1.0 * sc, 3),
                action="drain",
                params=_p(host="h3", to="h6", timeout=90.0),
            ),
            Phase(
                "dr_cycle",
                fault_class="dr_cycle",
                duration=round(1.0 * sc, 3),
                action="dr_cycle",
                params=_p(shard=SH_MEM),
            ),
            # traffic shape, not a disturbance: the zipfian read storm
            # lands AFTER the DR cycle so follower/bounded reads are
            # served by the re-imported membership (the hard case)
            Phase(
                "read_hot",
                duration=round(1.5 * sc, 3),
                action="read_hot",
                params=_p(
                    keys=24,
                    skew=j(1.1, 1.5),
                    readers=3,
                    bound_ticks=100,
                    shard=SH_MEM,
                ),
            ),
            # write-side zipfian skew (the read_hot mirror, ROADMAP 5c:
            # the write half): a hot-key write storm against the
            # audited shard — traffic shape, no fault class
            Phase(
                "write_hot",
                duration=round(1.2 * sc, 3),
                action="write_hot",
                params=_p(
                    keys=24,
                    skew=j(1.1, 1.5),
                    writers=3,
                    shard=SH_MEM,
                ),
            ),
            # sinusoidal offered-load swing (diurnal in miniature):
            # writers modulate their pacing over `period`; the ledger
            # row records the observed peak/trough committed rates
            Phase(
                "diurnal",
                duration=round(1.6 * sc, 3),
                action="diurnal",
                params=_p(
                    writers=3,
                    period=j(0.7, 1.1),
                    amp=j(0.5, 0.8),
                    shard=SH_MEM,
                ),
            ),
            # the elastic class: a zipfian write storm heats one shard
            # while the balancer's load-feedback loop watches the
            # gateway's per-shard evidence; the phase REQUIRES >=1
            # load-driven move and a post-move p99 drop (and that a
            # preceding quiet window fired ZERO moves)
            Phase(
                "elastic",
                fault_class="elastic",
                duration=round(2.0 * sc, 3),
                action="elastic",
                params=_p(
                    keys=24,
                    skew=j(1.2, 1.6),
                    writers=4,
                    shard=SH_MEM,
                    hot_p99_ms=60,
                    hot_submit=20,
                    min_samples=12,
                    hysteresis=2,
                    cooldown=8,
                    quiet_passes=4,
                    storm_s=round(2.5 * sc, 3),
                ),
            ),
            Phase("cooldown", duration=round(2.0 * sc, 3)),
        ]
        return DayPlan(seed=seed, gear="mini", phases=phases)

    @staticmethod
    def full(
        seed: int,
        *,
        hours: float = 1.0,
        gb: Optional[bool] = None,
    ) -> "DayPlan":
        """The hours-long day: warmup, then repeated disturbance cycles
        (churn -> stream chaos -> rolling restart -> alternating region
        drain) with a DR cycle every third round, sized so the whole
        schedule spans ~``hours``.  ``gb=None`` reads the
        ``DRAGONBOAT_BIGSTATE_GB`` gate; at the GB tier the FIRST
        stream-chaos phase carries a ~1 GiB on-disk payload behind an
        8 MB/s cap (the capped-stream economics measured in
        docs/BIGSTATE.md), later ones stay MB-scale so the day is churn-
        bound, not transfer-bound."""
        if gb is None:
            from ..bigstate import gb_tier

            gb = gb_tier()
        rng = Random(seed)

        def j(lo: float, hi: float) -> float:
            return round(rng.uniform(lo, hi), 3)

        # one cycle is ~5 min of scheduled day; steady traffic padding
        # dominates, so cycles scale linearly with the requested hours
        cycles = max(2, int(round(hours * 3600 / 300.0)))
        phases: List[Phase] = [Phase("warmup", duration=20.0)]
        for c in range(cycles):
            drain_from, drain_to = (
                ("h3", "h6") if c % 2 == 0 else ("h6", "h3")
            )
            payload_mb = 1024 if (gb and c == 0) else max(2, int(j(2, 6)))
            cap_mb = 8 if (gb and c == 0) else 4
            phases += [
                Phase(
                    f"c{c}/leader_churn",
                    fault_class="leader_churn",
                    duration=30.0,
                    faults=(
                        Fault("leader_kill", at=j(0.5, 2.0),
                              duration=j(1.0, 2.5), targets=(SH_MEM,)),
                        Fault("leader_transfer", at=j(6.0, 9.0),
                              targets=(SH_MEM,)),
                        Fault("member_cycle", at=j(10.0, 13.0),
                              duration=j(1.0, 2.0), targets=(SH_MEM,)),
                    ),
                ),
                Phase(
                    f"c{c}/stream_chaos",
                    fault_class="stream_chaos",
                    duration=30.0,
                    action="catchup_chaos",
                    params=_p(
                        payload_mb=payload_mb,
                        cap_mb=cap_mb,
                        kill_p=j(0.2, 0.5),
                        stall_p=j(0.2, 0.4),
                        stall_delay=j(0.005, 0.03),
                    ),
                ),
                Phase(
                    f"c{c}/rolling_restart",
                    fault_class="rolling_restart",
                    duration=30.0,
                    action="rolling_restart",
                    params=_p(hosts=3, grace=j(0.4, 0.9)),
                ),
                Phase(
                    f"c{c}/drain",
                    fault_class="drain",
                    duration=30.0,
                    action="drain",
                    params=_p(host=drain_from, to=drain_to, timeout=300.0),
                ),
            ]
            if c % 3 == 2:
                phases.append(
                    Phase(
                        f"c{c}/dr_cycle",
                        fault_class="dr_cycle",
                        duration=30.0,
                        action="dr_cycle",
                        params=_p(shard=SH_MEM),
                    )
                )
        # the mini gear guarantees every class once; the full gear must
        # too even at tiny `hours` (cycles>=2 fires all but dr_cycle)
        if not any(p.fault_class == "dr_cycle" for p in phases):
            phases.append(
                Phase(
                    "final/dr_cycle",
                    fault_class="dr_cycle",
                    duration=30.0,
                    action="dr_cycle",
                    params=_p(shard=SH_MEM),
                )
            )
        # one zipfian read storm per day (traffic shape, no fault class)
        phases.append(
            Phase(
                "read_hot",
                duration=30.0,
                action="read_hot",
                params=_p(
                    keys=24,
                    skew=j(1.1, 1.5),
                    readers=4,
                    bound_ticks=100,
                    shard=SH_MEM,
                ),
            )
        )
        # the adversarial-traffic tail (ISSUE 18): write-side skew,
        # a diurnal swing, then the elastic class — full-gear sized
        phases += [
            Phase(
                "write_hot",
                duration=30.0,
                action="write_hot",
                params=_p(
                    keys=24,
                    skew=j(1.1, 1.5),
                    writers=4,
                    shard=SH_MEM,
                ),
            ),
            Phase(
                "diurnal",
                duration=45.0,
                action="diurnal",
                params=_p(
                    writers=4,
                    period=j(8.0, 12.0),
                    amp=j(0.5, 0.8),
                    shard=SH_MEM,
                ),
            ),
            Phase(
                "elastic",
                fault_class="elastic",
                duration=30.0,
                action="elastic",
                params=_p(
                    keys=24,
                    skew=j(1.2, 1.6),
                    writers=5,
                    shard=SH_MEM,
                    hot_p99_ms=60,
                    hot_submit=20,
                    min_samples=12,
                    hysteresis=2,
                    cooldown=8,
                    quiet_passes=4,
                    storm_s=8.0,
                ),
            ),
        ]
        phases.append(Phase("cooldown", duration=15.0))
        return DayPlan(seed=seed, gear="full", phases=phases)

    @staticmethod
    def multiproc(seed: int) -> "DayPlan":
        """The cross-process gear (``DRAGONBOAT_MULTIPROC=1``,
        docs/SCENARIO.md "The multi-process gear"): a short schedule
        the ProcFleet dispatcher executes over real OS processes —
        whole-host SIGKILL, then an ASYMMETRIC partition (the PR 16
        directional wire kinds the in-proc transport can't express):
        a one-way ``asym_drop`` from the leader's process toward one
        follower, healed after ``window`` seconds, with the recovery
        SLA asserted after the heal and the Wing–Gong audit across the
        whole day.  Victims (which process leads, which follower is
        struck) are runtime-sampled and stay out of describe() by
        construction."""
        rng = Random(seed)

        def j(lo: float, hi: float) -> float:
            return round(rng.uniform(lo, hi), 3)

        phases = [
            Phase("warmup", duration=j(1.5, 2.5)),
            Phase(
                "proc_kill",
                fault_class="proc_kill9",
                duration=j(1.0, 2.0),
                action="proc_kill",
                params=_p(sla_ticks=4000),
            ),
            Phase(
                "asym_partition",
                fault_class="asym_partition",
                duration=j(1.0, 2.0),
                action="asym_partition",
                params=_p(
                    kind="asym_drop",
                    p=1.0,
                    window=j(1.2, 1.8),
                    sla_ticks=4000,
                ),
            ),
            Phase("cooldown", duration=j(0.8, 1.2)),
        ]
        return DayPlan(seed=seed, gear="multiproc", phases=phases)
