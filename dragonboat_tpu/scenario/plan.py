"""The production-day plan: a declarative, seeded phase schedule.

reference: the drummer/nemesis heritage (PAPER.md) — dragonboat's
credibility soak is scheduled churn plus a monitoring loop that keeps
repairing the fleet while traffic flows.  A :class:`DayPlan` is to the
scenario orchestrator what :class:`~dragonboat_tpu.faults.FaultPlan` is
to the nemesis: the complete, byte-canonical description of what will
be done to the cluster.  ``describe()`` is the determinism contract —
two plans built from the same seed and arguments are the SAME schedule
iff their describe() strings are byte-equal (tests/test_scenario.py
pins this), and every runtime-sampled victim (which host leads, which
stream a kill strikes) stays out of it by construction.

Two gears:

* :meth:`DayPlan.mini` — the tier-1-scale mini-day (~30-60 s, small
  fleet, every disturbance class fired at least once); ``scale < 1``
  shrinks it further for the ~10 s smoke gear.
* :meth:`DayPlan.full` — the env-gated hours-long day
  (``DRAGONBOAT_SOAK_DAY=1``, ``scripts/day_soak.sh``): repeated
  disturbance cycles sized to ``hours``, with the on-disk payload
  raised to GB scale when ``DRAGONBOAT_BIGSTATE_GB=1``
  (:func:`dragonboat_tpu.bigstate.gb_tier`).

The five disturbance classes (every gear fires each at least once):
``rolling_restart``, ``leader_churn``, ``stream_chaos``, ``drain``,
``dr_cycle`` — see docs/SCENARIO.md for the class catalog and the
ledger each phase emits.  ``read_hot`` is a TRAFFIC-SHAPE phase, not a
disturbance class (ROADMAP 5c): a zipfian hot-key read storm against
the audited shard, split across the read plane's consistency levels
(docs/READPLANE.md) — its ledger row carries the observed read-path
split.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from random import Random
from typing import List, Optional, Tuple

from ..faults import Fault

#: the scenario fleet's shard ids (fixed — the plan references them)
SH_MEM = 1   # in-memory AuditKV: audited gateway session traffic + DR
SH_DISK = 2  # on-disk OnDiskKV: big-state plane, witness + non-voting

#: the five disturbance classes a production day must fire
DISTURBANCE_CLASSES = (
    "rolling_restart",
    "leader_churn",
    "stream_chaos",
    "drain",
    "dr_cycle",
)


@dataclass(frozen=True)
class Phase:
    """One phase of the day.

    ``action`` names an orchestrator maneuver the runner executes
    (``rolling_restart`` / ``catchup_chaos`` / ``drain`` / ``dr_cycle``
    / ``read_hot`` or empty for traffic-only phases); ``faults`` is a nemesis
    sub-plan executed via :meth:`FaultController.run_phase` before the
    action; ``duration`` is the minimum wall time of the phase (traffic
    keeps flowing until it elapses, so even a fast action yields a
    measurable throughput window).  ``params`` is a sorted key/value
    tuple — part of the byte-canonical describe()."""

    name: str
    fault_class: str = ""
    duration: float = 0.0
    action: str = ""
    params: Tuple[Tuple[str, object], ...] = ()
    faults: Tuple[Fault, ...] = ()

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default

    def describe(self) -> str:
        ps = ",".join(f"{k}={v!r}" for k, v in self.params)
        fs = ";".join(f.describe() for f in self.faults)
        return (
            f"phase {self.name} class={self.fault_class} "
            f"dur={self.duration:g} action={self.action} "
            f"params({ps}) faults[{fs}]"
        )


def _p(**kw) -> Tuple[Tuple[str, object], ...]:
    return tuple(sorted(kw.items()))


@dataclass
class DayPlan:
    """An ordered production-day schedule (see module docstring)."""

    seed: int
    gear: str
    phases: List[Phase] = field(default_factory=list)

    def describe(self) -> str:
        head = f"dayplan gear={self.gear} seed={self.seed}"
        return "\n".join([head] + [p.describe() for p in self.phases])

    def classes_planned(self) -> Tuple[str, ...]:
        return tuple(
            sorted({p.fault_class for p in self.phases if p.fault_class})
        )

    # ------------------------------------------------------------------
    # builders
    # ------------------------------------------------------------------
    @staticmethod
    def mini(seed: int, *, scale: float = 1.0) -> "DayPlan":
        """The tier-1 mini-day.  ``scale`` shrinks durations, payload
        and the restart sweep (the smoke gear uses ~0.4); every
        disturbance class still fires at least once at any scale."""
        rng = Random(seed)

        def j(lo: float, hi: float) -> float:
            # schedule jitter, rounded so describe() stays byte-stable
            return round(rng.uniform(lo, hi), 3)

        sc = max(0.2, float(scale))
        restarts = 3 if sc >= 0.75 else 1
        payload_mb = max(1, int(round(3 * sc)))
        phases = [
            Phase("warmup", duration=round(3.0 * sc, 3)),
            Phase(
                "rolling_restart",
                fault_class="rolling_restart",
                duration=round(1.0 * sc, 3),
                action="rolling_restart",
                params=_p(hosts=restarts, grace=j(0.3, 0.6)),
            ),
            Phase(
                "leader_churn",
                fault_class="leader_churn",
                duration=round(1.5 * sc, 3),
                action="",
                faults=(
                    Fault("leader_kill", at=j(0.1, 0.4),
                          duration=j(0.8, 1.4), targets=(SH_MEM,)),
                    Fault("leader_transfer", at=j(2.6, 3.2),
                          targets=(SH_MEM,)),
                ),
            ),
            Phase(
                "stream_chaos",
                fault_class="stream_chaos",
                duration=round(1.0 * sc, 3),
                action="catchup_chaos",
                params=_p(
                    payload_mb=payload_mb,
                    cap_mb=4,
                    kill_p=j(0.3, 0.5),
                    stall_p=j(0.2, 0.4),
                    stall_delay=j(0.005, 0.02),
                ),
            ),
            Phase(
                "drain",
                fault_class="drain",
                duration=round(1.0 * sc, 3),
                action="drain",
                params=_p(host="h3", to="h6", timeout=90.0),
            ),
            Phase(
                "dr_cycle",
                fault_class="dr_cycle",
                duration=round(1.0 * sc, 3),
                action="dr_cycle",
                params=_p(shard=SH_MEM),
            ),
            # traffic shape, not a disturbance: the zipfian read storm
            # lands AFTER the DR cycle so follower/bounded reads are
            # served by the re-imported membership (the hard case)
            Phase(
                "read_hot",
                duration=round(1.5 * sc, 3),
                action="read_hot",
                params=_p(
                    keys=24,
                    skew=j(1.1, 1.5),
                    readers=3,
                    bound_ticks=100,
                    shard=SH_MEM,
                ),
            ),
            Phase("cooldown", duration=round(2.0 * sc, 3)),
        ]
        return DayPlan(seed=seed, gear="mini", phases=phases)

    @staticmethod
    def full(
        seed: int,
        *,
        hours: float = 1.0,
        gb: Optional[bool] = None,
    ) -> "DayPlan":
        """The hours-long day: warmup, then repeated disturbance cycles
        (churn -> stream chaos -> rolling restart -> alternating region
        drain) with a DR cycle every third round, sized so the whole
        schedule spans ~``hours``.  ``gb=None`` reads the
        ``DRAGONBOAT_BIGSTATE_GB`` gate; at the GB tier the FIRST
        stream-chaos phase carries a ~1 GiB on-disk payload behind an
        8 MB/s cap (the capped-stream economics measured in
        docs/BIGSTATE.md), later ones stay MB-scale so the day is churn-
        bound, not transfer-bound."""
        if gb is None:
            from ..bigstate import gb_tier

            gb = gb_tier()
        rng = Random(seed)

        def j(lo: float, hi: float) -> float:
            return round(rng.uniform(lo, hi), 3)

        # one cycle is ~5 min of scheduled day; steady traffic padding
        # dominates, so cycles scale linearly with the requested hours
        cycles = max(2, int(round(hours * 3600 / 300.0)))
        phases: List[Phase] = [Phase("warmup", duration=20.0)]
        for c in range(cycles):
            drain_from, drain_to = (
                ("h3", "h6") if c % 2 == 0 else ("h6", "h3")
            )
            payload_mb = 1024 if (gb and c == 0) else max(2, int(j(2, 6)))
            cap_mb = 8 if (gb and c == 0) else 4
            phases += [
                Phase(
                    f"c{c}/leader_churn",
                    fault_class="leader_churn",
                    duration=30.0,
                    faults=(
                        Fault("leader_kill", at=j(0.5, 2.0),
                              duration=j(1.0, 2.5), targets=(SH_MEM,)),
                        Fault("leader_transfer", at=j(6.0, 9.0),
                              targets=(SH_MEM,)),
                        Fault("member_cycle", at=j(10.0, 13.0),
                              duration=j(1.0, 2.0), targets=(SH_MEM,)),
                    ),
                ),
                Phase(
                    f"c{c}/stream_chaos",
                    fault_class="stream_chaos",
                    duration=30.0,
                    action="catchup_chaos",
                    params=_p(
                        payload_mb=payload_mb,
                        cap_mb=cap_mb,
                        kill_p=j(0.2, 0.5),
                        stall_p=j(0.2, 0.4),
                        stall_delay=j(0.005, 0.03),
                    ),
                ),
                Phase(
                    f"c{c}/rolling_restart",
                    fault_class="rolling_restart",
                    duration=30.0,
                    action="rolling_restart",
                    params=_p(hosts=3, grace=j(0.4, 0.9)),
                ),
                Phase(
                    f"c{c}/drain",
                    fault_class="drain",
                    duration=30.0,
                    action="drain",
                    params=_p(host=drain_from, to=drain_to, timeout=300.0),
                ),
            ]
            if c % 3 == 2:
                phases.append(
                    Phase(
                        f"c{c}/dr_cycle",
                        fault_class="dr_cycle",
                        duration=30.0,
                        action="dr_cycle",
                        params=_p(shard=SH_MEM),
                    )
                )
        # the mini gear guarantees every class once; the full gear must
        # too even at tiny `hours` (cycles>=2 fires all but dr_cycle)
        if not any(p.fault_class == "dr_cycle" for p in phases):
            phases.append(
                Phase(
                    "final/dr_cycle",
                    fault_class="dr_cycle",
                    duration=30.0,
                    action="dr_cycle",
                    params=_p(shard=SH_MEM),
                )
            )
        # one zipfian read storm per day (traffic shape, no fault class)
        phases.append(
            Phase(
                "read_hot",
                duration=30.0,
                action="read_hot",
                params=_p(
                    keys=24,
                    skew=j(1.1, 1.5),
                    readers=4,
                    bound_ticks=100,
                    shard=SH_MEM,
                ),
            )
        )
        phases.append(Phase("cooldown", duration=15.0))
        return DayPlan(seed=seed, gear="full", phases=phases)
