"""Pending-operation futures connecting the public API to the step loop.

reference: request.go (RequestState, pendingProposal, pendingReadIndex,
pendingConfigChange, pendingSnapshot, pendingLeaderTransfer) [U].

Timeouts are logical: deadlines are in ticks, swept by the node's tick
path, so behavior is reproducible and cheap at high request rates.
"""
from __future__ import annotations

import enum
import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .client import Session
from .pb import Entry, EntryType, SystemCtx
from .statemachine import Result

# Pending-table keys ride Entry.key across every boundary as a uint64
# (transport/wire._w_entry, the tan WAL, kvlogdb — docs/PARITY.md 64-bit
# policy), and read-index keys additionally split into two sub-2^31
# SystemCtx halves for the device inbox's int32 hint lanes
# (PendingReadIndex.read).  Bases are therefore 61-bit: keys stay below
# 2^62 with >= 2^61 increments of headroom, the low/high ctx split stays
# injective, and the wire codecs never see an out-of-range value.
KEY_BASE_BITS = 61
_SYSRAND = random.SystemRandom()


def random_key_base() -> int:
    """Random per-table key base (reference: every node seeds its
    keyGenerator randomly at start [U]).  Sequential-from-zero keys were
    the ROADMAP latent: every table of every replica counted 1, 2, 3 …,
    so a follower's brief in-flight local proposal could share a key
    with a leader-origin committed entry and ``applied(e.key, …)`` would
    complete the WRONG future — a false ack.  With per-table random
    bases a cross-table/cross-replica/cross-incarnation collision needs
    the counters' live windows to overlap within ~2^61."""
    return _SYSRAND.getrandbits(KEY_BASE_BITS)


class RequestError(Exception):
    pass


class ShardNotFound(RequestError):
    pass


class ShardNotReady(RequestError):
    pass


class InvalidTarget(RequestError):
    pass


class SystemBusy(RequestError):
    pass


class RequestResultCode(enum.IntEnum):
    TIMEOUT = 0
    COMPLETED = 1
    TERMINATED = 2
    REJECTED = 3
    DROPPED = 4
    ABORTED = 5
    COMMITTED = 6  # notify-commit mode: committed but not yet applied


class RequestState:
    """A single pending operation's future (reference: RequestState [U]).

    ``span`` is the request's root trace span (obs/; None when tracing
    is off or the request unsampled): every completion path funnels
    through ``notify``, so ending it here covers applied, dropped,
    timed-out, terminated and sealed futures alike."""

    __slots__ = ("key", "deadline", "_event", "code", "result", "_committed",
                 "span")

    def __init__(self, key: int, deadline: int):
        self.key = key
        self.deadline = deadline
        self._event = threading.Event()
        self.code: Optional[RequestResultCode] = None
        self.result: Result = Result()
        self._committed = False
        self.span = None

    # -- completion (engine side) ---------------------------------------
    def notify(self, code: RequestResultCode, result: Optional[Result] = None):
        self.code = code
        if result is not None:
            self.result = result
        s = self.span
        if s is not None:
            s.end(status=code.name if code is not None else "unknown")
        self._event.set()

    def notify_committed(self):
        self._committed = True

    # -- waiting (client side) -------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> RequestResultCode:
        if not self._event.wait(timeout):
            return RequestResultCode.TIMEOUT
        return self.code  # type: ignore[return-value]

    def completed(self) -> bool:
        return self.code == RequestResultCode.COMPLETED


# deadline-hint sentinel: "no pending deadline".  An int (not inf) so
# the lock-free `tick >= hint[0]` probe stays int-vs-int.  Far above
# any reachable tick count (ticks are ~100 ms; 2^62 ticks is ~1.4e10
# years) yet small enough to never overflow arithmetic around it.
NO_DEADLINE = 1 << 62


class _PendingBase:
    __slots__ = ("_lock", "_next_key", "_pending", "_hint")

    def __init__(
        self,
        lock: Optional[threading.Lock] = None,
        key_base: Optional[int] = None,
        deadline_hint: Optional[list] = None,
    ):
        # a node's five tables share one lock (pass it in): contention
        # is per-replica and tiny, while 4 saved locks x 50k rows is
        # real host footprint
        self._lock = lock if lock is not None else threading.Lock()
        self._pending: Dict[int, RequestState] = {}  # guarded-by: _lock
        # randomized unless the owner supplies one (Node salts with the
        # replica id); see random_key_base for why 0 was a correctness bug
        self._next_key = (  # guarded-by: _lock
            random_key_base() if key_base is None else key_base
        )
        # earliest-pending-deadline hint, shared across a node's five
        # tables (a 1-element list cell, like the lock): _alloc lowers
        # it under _lock; gc_tables re-arms it after a sweep.  The tick
        # path probes it LOCK-FREE (`tick >= hint[0]`) — a stale-high
        # read (probe raced a concurrent _alloc's lowering) only delays
        # that future's timeout to the next tick sweep, the same benign
        # race the lock-free `_pending` probe in gc() already accepts;
        # a stale-low read (pop/seal/drop_all never raise it) costs one
        # no-op sweep that re-arms it.
        self._hint = deadline_hint if deadline_hint is not None else [
            NO_DEADLINE
        ]

    def _alloc(self, deadline: int) -> RequestState:
        with self._lock:
            self._next_key += 1
            rs = RequestState(self._next_key, deadline)
            self._pending[self._next_key] = rs
            if deadline < self._hint[0]:
                self._hint[0] = deadline  # guarded-by: _lock
            return rs

    def pop(self, key: int) -> Optional[RequestState]:
        with self._lock:
            return self._pending.pop(key, None)

    def dropped(self, key: int) -> None:
        rs = self.pop(key)
        if rs is not None:
            rs.notify(RequestResultCode.DROPPED)

    def gc(self, now_tick: int) -> None:
        # raftlint: ignore[guarded-by] lock-free empty probe (benign race, see below)
        if not self._pending:
            # lock-free empty check: the sweep runs five-tables deep per
            # tick per replica row — at 50k rows that is millions of
            # no-op lock acquisitions per second.  The race is benign: a
            # request registered concurrently is swept next tick.
            return
        with self._lock:
            self._gc_locked(now_tick)

    def _gc_locked(self, now_tick: int) -> int:  # guarded-by: _lock
        """Sweep under a held ``self._lock`` and return the surviving
        minimum deadline (``NO_DEADLINE`` when empty) so batched
        callers (:func:`gc_tables`) can re-arm the shared hint."""
        expired = [
            k for k, rs in self._pending.items() if rs.deadline <= now_tick
        ]
        for k in expired:
            self._pending.pop(k).notify(RequestResultCode.TIMEOUT)
        if expired:
            self._gc_extra(set(expired))
        nd = NO_DEADLINE
        for rs in self._pending.values():
            if rs.deadline < nd:
                nd = rs.deadline
        return nd

    def _gc_extra(self, expired_keys) -> None:  # guarded-by: _lock
        """Subclass hook, called under self._lock, to drop side-table state
        for expired keys."""

    def drop_all(self, code: RequestResultCode = RequestResultCode.TERMINATED):
        with self._lock:
            keys = set(self._pending)
            for rs in self._pending.values():
                rs.notify(code)
            self._pending.clear()
            if keys:
                self._gc_extra(keys)

    def seal(self, rs: RequestState) -> None:
        """Terminate a just-allocated future whose node stopped
        concurrently.  ``Node.stop()`` runs ``drop_all`` right after
        setting ``stopped``; a producer that allocated AFTER the sweep
        would otherwise leave a future that no step loop will ever
        complete and no tick will ever GC — a hung caller and a leaked
        table entry (the history recorder counts on Terminated being
        delivered).  Pop-once keeps the double-notify race with
        drop_all benign.  ``_gc_extra`` runs UNCONDITIONALLY: a
        read-index allocates its future and inserts its ctx-map entry
        under two separate lock holds, so drop_all can sweep between
        them — the swept key's late ctx insert must still be cleaned
        here even though the future itself is already notified."""
        with self._lock:
            notified = self._pending.pop(rs.key, None) is not None
            self._gc_extra({rs.key})
        if notified:
            rs.notify(RequestResultCode.TERMINATED)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


def gc_tables(tables, hint, now_tick: int) -> None:
    """One hint-gated sweep over a node's pending tables — the batched
    replacement for five per-table ``gc()`` calls per tick/generation.

    ``tables`` must share ONE lock and ONE deadline-hint cell (the
    ``Node`` construction; asserted under ``__debug__``): the whole
    sweep then runs under a single lock acquisition, and the hint
    re-arm cannot race a concurrent ``_alloc``'s lowering (both are
    serialized by the same lock).

    Exactness (the monotone-deadline argument, kept honest): deadlines
    are fixed at allocation and ``now_tick`` is monotone, so a future
    times out at exactly the first sweep whose ``now_tick`` reaches its
    deadline.  The hint is the min pending deadline, therefore the
    first tick at which ANY future could expire is precisely the first
    tick at which this function sweeps — every timeout is delivered at
    the same tick value the old sweep-every-tick loop delivered it at,
    while ticks below the hint (the overwhelming majority) cost one
    int compare instead of five lock-acquiring sweeps.
    """
    if now_tick < hint[0]:
        return
    lock = tables[0]._lock
    assert all(t._lock is lock and t._hint is hint for t in tables), (
        "gc_tables requires tables sharing one lock + hint cell"
    )
    with lock:
        nd = NO_DEADLINE
        for t in tables:
            d = t._gc_locked(now_tick)
            if d < nd:
                nd = d
        hint[0] = nd  # guarded-by: the shared tables lock


class PendingProposal(_PendingBase):
    __slots__ = ()
    """reference: pendingProposal (sharded by key in the reference; a
    single dict suffices under the GIL) [U]."""

    def propose(
        self, session: Session, cmd: bytes, deadline: int
    ) -> Tuple[Entry, RequestState]:
        rs = self._alloc(deadline)
        entry = Entry(
            type=EntryType.APPLICATION,
            key=rs.key,
            client_id=session.client_id,
            series_id=session.series_id,
            responded_to=session.responded_to,
            cmd=cmd,
        )
        return entry, rs

    def applied(self, key: int, result: Result, rejected: bool) -> None:
        rs = self.pop(key)
        if rs is None:
            return
        code = (
            RequestResultCode.REJECTED if rejected else RequestResultCode.COMPLETED
        )
        rs.notify(code, result)

    def committed(self, key: int) -> None:
        with self._lock:
            rs = self._pending.get(key)
        if rs is not None:
            rs.notify_committed()


class PendingReadIndex(_PendingBase):
    __slots__ = ("_ctx_map", "_waiting")
    """reference: pendingReadIndex [U].  Two stages: (1) ctx confirmed by
    quorum -> learn the read index; (2) applied index reaches it ->
    complete."""

    def __init__(
        self,
        lock: Optional[threading.Lock] = None,
        key_base: Optional[int] = None,
        deadline_hint: Optional[list] = None,
    ):
        super().__init__(lock, key_base, deadline_hint)
        self._ctx_map: Dict[Tuple[int, int], int] = {}  # ctx->key; guarded-by: _lock
        self._waiting: List[Tuple[int, int]] = []  # (read_index, key); guarded-by: _lock

    def _gc_extra(self, expired_keys) -> None:  # guarded-by: _lock
        self._ctx_map = {
            c: k for c, k in self._ctx_map.items() if k not in expired_keys
        }
        self._waiting = [
            (i, k) for i, k in self._waiting if k not in expired_keys
        ]

    def read(self, deadline: int) -> Tuple[SystemCtx, RequestState]:
        rs = self._alloc(deadline)
        # each half stays < 2^31 so the ctx can ride the device inbox's
        # int32 hint fields (ops/engine.py device ReadIndex) and every
        # wire codec without sign trouble; keys are sequential from a
        # 61-bit randomized base, so the split stays injective
        ctx = SystemCtx(
            low=rs.key & 0x7FFFFFFF, high=(rs.key >> 31) & 0x7FFFFFFF
        )
        with self._lock:
            self._ctx_map[(ctx.low, ctx.high)] = rs.key
        return ctx, rs

    def confirmed(self, ctx: SystemCtx, index: int) -> None:
        with self._lock:
            key = self._ctx_map.pop((ctx.low, ctx.high), None)
            if key is None or key not in self._pending:
                return
            self._waiting.append((index, key))

    def dropped(self, ctx: SystemCtx) -> None:
        with self._lock:
            key = self._ctx_map.pop((ctx.low, ctx.high), None)
        if key is None:
            return
        rs = self.pop(key)
        if rs is not None:
            rs.notify(RequestResultCode.DROPPED)

    def applied(self, applied_index: int) -> None:
        """Called as the apply loop advances; completes reads whose index
        has been reached."""
        ready: List[int] = []
        with self._lock:
            still = []
            for index, key in self._waiting:
                if index <= applied_index:
                    ready.append(key)
                else:
                    still.append((index, key))
            self._waiting = still
        for key in ready:
            rs = self.pop(key)
            if rs is not None:
                rs.notify(RequestResultCode.COMPLETED)


class PendingConfigChange(_PendingBase):
    __slots__ = ()
    def request(self, cc, deadline: int) -> Tuple[int, RequestState]:
        rs = self._alloc(deadline)
        return rs.key, rs

    def applied(self, key: int, rejected: bool) -> None:
        rs = self.pop(key)
        if rs is None:
            return
        rs.notify(
            RequestResultCode.REJECTED if rejected else RequestResultCode.COMPLETED
        )


class PendingSnapshot(_PendingBase):
    __slots__ = ()
    def request(self, deadline: int) -> RequestState:
        return self._alloc(deadline)

    def done(self, key: int, index: int, failed: bool = False) -> None:
        rs = self.pop(key)
        if rs is None:
            return
        if failed:
            rs.notify(RequestResultCode.REJECTED)
        else:
            rs.notify(RequestResultCode.COMPLETED, Result(value=index))


class PendingLeaderTransfer(_PendingBase):
    __slots__ = ()
    def request(self, target: int, deadline: int) -> RequestState:
        return self._alloc(deadline)

    def notify_leader(self, leader_id: int) -> None:
        with self._lock:
            keys = list(self._pending)
            for k in keys:
                self._pending.pop(k).notify(
                    RequestResultCode.COMPLETED, Result(value=leader_id)
                )
