"""Non-blocking fan-out of raft/system events to user listeners.

reference: event.go [U].  Listener callbacks run on a dedicated thread so
a slow listener can never stall the step loop; the queue is bounded and
drops under pressure, as the reference does — every drop increments
``event_fanout_dropped_total`` (the registry is passed in by NodeHost)
and the warning names the callback that lost its event.

``tap`` is the flight recorder's synchronous hook (obs/recorder.py): it
sees every SYSTEM event at post time, including ones the bounded queue
would drop — a recorder that misses state transitions under pressure
would be useless exactly when it matters.  ``add_tap``/``remove_tap``
attach further synchronous taps at runtime (the gateway's routing-cache
invalidation rides one); unlike the recorder tap these ALSO see
``leader_updated``, because leader identity is exactly what a routing
cache keys on.

Thread-safety is by construction, not by lock: ``_q``/``_stop`` are
inherently thread-safe, the listener/tap fields are written once in
``__init__`` and only read afterwards, and ``_taps`` is a copy-on-write
tuple (readers grab the whole tuple in one attribute load; writers swap
a fresh tuple under ``_taps_lock``) — so there is nothing here for a
``# guarded-by:`` annotation to guard on the read side.  The discipline
that DOES bind
this module is raftlint's ``block-under-lock`` rule: the PR 4 close()
deadlock (a blocking ``put`` wedged against a full queue) is its seeded
true-positive fixture (tests/test_analysis.py), and the non-blocking
``put_nowait``/timed-``get`` shape below is the sanctioned pattern.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

from .logger import get_logger
from .raftio import IRaftEventListener, ISystemEventListener, LeaderInfo

_log = get_logger("nodehost")


class EventFanout:
    # NOT a subclass of ISystemEventListener: its concrete no-op
    # methods would shadow the __getattr__ forwarding below (normal
    # attribute lookup finds the inherited no-op, __getattr__ never
    # fires), silently dropping every system event — which is exactly
    # what happened until the balance control plane's event drive
    # caught it.  Duck typing is the contract; nothing isinstance-checks
    # the fanout.
    def __init__(
        self,
        raft_listener: Optional[IRaftEventListener] = None,
        system_listener: Optional[ISystemEventListener] = None,
        maxsize: int = 4096,
        metrics=None,
        tap: Optional[Callable] = None,
    ):
        self.raft_listener = raft_listener
        self.system_listener = system_listener
        self.tap = tap
        # runtime-attached synchronous taps (copy-on-write tuple; see
        # module docstring): called as fn(name, args) for every system
        # event AND leader_updated
        self._taps: tuple = ()
        self._taps_lock = threading.Lock()
        self._dropped = (
            metrics.counter("event_fanout_dropped_total")
            if metrics is not None
            else None
        )
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._main, daemon=True, name="tpu-raft-events"
        )
        self._thread.start()

    def add_tap(self, fn: Callable) -> None:
        """Attach a synchronous tap ``fn(name, args)``.  Taps run on the
        POSTING thread (the step worker for most events), so they must
        be cheap and non-blocking — a dict swap, a counter, never a
        lock that request paths contend on."""
        with self._taps_lock:
            self._taps = (*self._taps, fn)

    def remove_tap(self, fn: Callable) -> None:
        with self._taps_lock:
            self._taps = tuple(t for t in self._taps if t is not fn)

    def _run_taps(self, name: str, args) -> None:
        for t in self._taps:  # one attribute load; tuple is immutable
            try:
                t(name, args)
            except Exception:  # noqa: BLE001 — observability/routing
                # taps must never break the event path
                _log.exception("event tap raised")

    def close(self) -> None:
        self._stop.set()
        try:
            # non-blocking: a full queue means the drain thread has
            # items to chew through and will see _stop within one get
            # timeout; a blocking put here deadlocks when the thread
            # exits via the _stop check with the queue still full
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=1.0)

    def _main(self) -> None:
        # the get must be timed: when close()'s sentinel is dropped by
        # a full queue, an untimed get would block forever once the
        # backlog drains and the thread would leak past join()
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — listener bugs must not kill us
                _log.exception("event listener raised")

    def _post(self, fn, *args) -> None:
        try:
            self._q.put_nowait((fn, args))
        except queue.Full:
            if self._dropped is not None:
                self._dropped.add()
            _log.warning(
                "event queue full, dropping event for %s",
                getattr(fn, "__qualname__", None)
                or getattr(fn, "__name__", repr(fn)),
            )

    # -- raft events ------------------------------------------------------
    def leader_updated(self, info: LeaderInfo) -> None:
        if self._taps:
            self._run_taps("leader_updated", (info,))
        if self.raft_listener is not None:
            self._post(self.raft_listener.leader_updated, info)

    # -- system events ----------------------------------------------------
    def __getattr__(self, name):
        # forward any ISystemEventListener callback asynchronously
        if name.startswith("_"):
            raise AttributeError(name)
        base = getattr(ISystemEventListener, name, None)
        if base is None:
            raise AttributeError(name)

        def forward(*args):
            tap = self.tap
            if tap is not None:
                try:
                    tap(name, args)
                except Exception:  # noqa: BLE001 — observability must
                    # never break the event path
                    _log.exception("event tap raised")
            if self._taps:
                self._run_taps(name, args)
            if self.system_listener is not None:
                target = getattr(self.system_listener, name, None)
                if target is not None:
                    self._post(target, *args)

        return forward
