"""Non-blocking fan-out of raft/system events to user listeners.

reference: event.go [U].  Listener callbacks run on a dedicated thread so
a slow listener can never stall the step loop; the queue is bounded and
drops (with a log line) under pressure, as the reference does.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

from .logger import get_logger
from .raftio import IRaftEventListener, ISystemEventListener, LeaderInfo

_log = get_logger("nodehost")


class EventFanout:
    # NOT a subclass of ISystemEventListener: its concrete no-op
    # methods would shadow the __getattr__ forwarding below (normal
    # attribute lookup finds the inherited no-op, __getattr__ never
    # fires), silently dropping every system event — which is exactly
    # what happened until the balance control plane's event drive
    # caught it.  Duck typing is the contract; nothing isinstance-checks
    # the fanout.
    def __init__(
        self,
        raft_listener: Optional[IRaftEventListener] = None,
        system_listener: Optional[ISystemEventListener] = None,
        maxsize: int = 4096,
    ):
        self.raft_listener = raft_listener
        self.system_listener = system_listener
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._main, daemon=True, name="tpu-raft-events"
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=1.0)

    def _main(self) -> None:
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — listener bugs must not kill us
                _log.exception("event listener raised")

    def _post(self, fn, *args) -> None:
        try:
            self._q.put_nowait((fn, args))
        except queue.Full:
            _log.warning("event queue full, dropping event")

    # -- raft events ------------------------------------------------------
    def leader_updated(self, info: LeaderInfo) -> None:
        if self.raft_listener is not None:
            self._post(self.raft_listener.leader_updated, info)

    # -- system events ----------------------------------------------------
    def __getattr__(self, name):
        # forward any ISystemEventListener callback asynchronously
        if name.startswith("_"):
            raise AttributeError(name)
        base = getattr(ISystemEventListener, name, None)
        if base is None:
            raise AttributeError(name)

        def forward(*args):
            if self.system_listener is not None:
                target = getattr(self.system_listener, name, None)
                if target is not None:
                    self._post(target, *args)

        return forward
