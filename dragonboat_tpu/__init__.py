"""tpu-raft: a TPU-native multi-group Raft consensus framework.

A brand-new framework with the capabilities of dragonboat
(awesome-golang/dragonboat, upstream lni/dragonboat): NodeHost hosting
many independent raft shards, leader election, log replication,
linearizable reads, client sessions, snapshotting, membership change,
batched WAL, pluggable transport — with the pure raft step function
runnable as a vectorized JAX kernel over [groups x peers] state tensors
sharded across TPU chips.
"""
__version__ = "0.1.0"

from .balance import (
    Balancer,
    BalanceAborted,
    DrainTimeout,
    MoveFailed,
)
from .client import LatencyBudget, Session, call_with_retry, propose_with_retry
from .config import Config, EngineConfig, ExpertConfig, GossipConfig, NodeHostConfig
from .gateway import (
    ClientHandle,
    Gateway,
    GatewayBusy,
    GatewayClosed,
    GatewayConfig,
)
from .faults import (
    RECOVERY_STATS,
    Fault,
    FaultController,
    FaultPlan,
    RecoveryStats,
    RecoverySLAAborted,
    RecoverySLAViolation,
    assert_recovery_sla,
)
from .nodehost import (
    NodeHost,
    NodeHostClosed,
    RequestDropped,
    RequestRejected,
    RequestTerminated,
    TimeoutError_,
)
from .pb import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
    Update,
)
from .request import (
    RequestError,
    RequestResultCode,
    RequestState,
    ShardNotFound,
    SystemBusy,
)
from .statemachine import (
    IConcurrentStateMachine,
    IOnDiskStateMachine,
    IStateMachine,
    Result,
    SMEntry,
    SnapshotStopped,
)

__all__ = [
    "RECOVERY_STATS",
    "RecoveryStats",
    "Balancer",
    "BalanceAborted",
    "DrainTimeout",
    "MoveFailed",
    "LatencyBudget",
    "Session",
    "call_with_retry",
    "propose_with_retry",
    "Config",
    "EngineConfig",
    "ExpertConfig",
    "GossipConfig",
    "NodeHostConfig",
    "ClientHandle",
    "Gateway",
    "GatewayBusy",
    "GatewayClosed",
    "GatewayConfig",
    "NodeHost",
    "NodeHostClosed",
    "RequestDropped",
    "RequestRejected",
    "RequestTerminated",
    "TimeoutError_",
    "ConfigChange",
    "ConfigChangeType",
    "Entry",
    "EntryType",
    "Membership",
    "Message",
    "MessageType",
    "Snapshot",
    "State",
    "Update",
    "RequestError",
    "RequestResultCode",
    "RequestState",
    "ShardNotFound",
    "SystemBusy",
    "IConcurrentStateMachine",
    "IOnDiskStateMachine",
    "IStateMachine",
    "Result",
    "SMEntry",
    "SnapshotStopped",
]
