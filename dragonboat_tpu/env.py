"""NodeHost directory environment: exclusive lock + deployment id.

reference: internal/server/environment.go [U] — flock-based dir locking
(two NodeHost processes must never share a data dir) and deployment-ID
persistence (a nodehost dir created under one deployment must refuse to
open under another; the transport also stamps/validates the id on every
batch).
"""
from __future__ import annotations

import fcntl
import os
from typing import Optional

LOCK_FILENAME = "LOCK"
DEPLOYMENT_FILENAME = "DEPLOYMENT.ID"


class DirLockedError(Exception):
    """Another NodeHost holds this nodehost dir."""


class DeploymentIDMismatch(Exception):
    """The dir was created under a different deployment id."""


class Env:
    def __init__(self, nodehost_dir: str, deployment_id: int = 0):
        self.dir = nodehost_dir
        os.makedirs(nodehost_dir, exist_ok=True)
        self._lock_f = open(os.path.join(nodehost_dir, LOCK_FILENAME), "a+")
        try:
            fcntl.flock(self._lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_f.close()
            raise DirLockedError(
                f"nodehost dir already locked: {nodehost_dir}"
            )
        self._check_deployment_id(deployment_id)

    def _check_deployment_id(self, deployment_id: int) -> None:
        path = os.path.join(self.dir, DEPLOYMENT_FILENAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                stored = int(f.read().strip() or "0")
        except ValueError:
            self.close()
            raise DeploymentIDMismatch(
                f"corrupt deployment-id file in {self.dir}"
            )
        except FileNotFoundError:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(str(deployment_id))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return
        if stored != deployment_id:
            self.close()
            raise DeploymentIDMismatch(
                f"dir {self.dir} belongs to deployment {stored}, "
                f"not {deployment_id}"
            )

    def close(self) -> None:
        if self._lock_f is not None:
            try:
                fcntl.flock(self._lock_f, fcntl.LOCK_UN)
            except OSError:
                pass
            self._lock_f.close()
            self._lock_f = None
