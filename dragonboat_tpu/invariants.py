"""Env-gated internal invariant assertions.

reference: internal/invariants [U] — build-tag-gated checks that run in
race/monkeytest CI builds and compile away in production.  Python has
no build tags; the switch is the ``DRAGONBOAT_TPU_INVARIANTS`` env var
(the test suite turns it on in conftest.py, production defaults off so
the hot path pays one module-level bool).

Usage:
    from .invariants import check
    check(new_commit >= old_commit, "commit moved backwards: %d -> %d",
          old_commit, new_commit)
"""
from __future__ import annotations

import os

ENABLED = os.environ.get("DRAGONBOAT_TPU_INVARIANTS", "0") not in ("", "0")


class InvariantViolation(AssertionError):
    """An internal consistency check failed — always a bug, never an
    environmental condition; fail loudly."""


def check(cond: bool, msg: str, *args) -> None:
    if ENABLED and not cond:
        raise InvariantViolation(msg % args if args else msg)


def enable(on: bool = True) -> None:
    """Programmatic switch (tests)."""
    global ENABLED
    ENABLED = on
