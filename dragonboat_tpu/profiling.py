"""Kernel tracing/profiling hooks (SURVEY §5.1).

The reference's observability for hot loops is Go pprof; the TPU-native
equivalent is the JAX/XLA device profiler (xplane traces viewable in
TensorBoard/xprof).  This module is a thin, dependency-light wrapper so
the engine and the bench can be traced without importing jax at module
scope anywhere in the host runtime.

Usage:
    from dragonboat_tpu.profiling import trace, annotate

    with trace("/tmp/raft-xplane"):
        ... run a workload ...            # device trace captured

    with annotate("device-step"):         # named region in the trace
        ... kernel launch ...

``BENCH_PROFILE=<dir> python bench.py`` captures the timed window.
"""
from __future__ import annotations

import contextlib


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace (xplane) into ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region for the device trace (no-op cost off-profile)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
