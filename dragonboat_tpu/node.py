"""Per-(shard, replica) node: binds the pure raft peer to queues, the RSM,
the LogDB and the transport.

reference: node.go [U].  Threading contract (same as the reference's):
``step()``/``process_update()`` run only on the one step worker that owns
this shard; ``apply()`` only on its apply worker; public-API threads touch
only the thread-safe queues and pending tables.
"""
from __future__ import annotations

import dataclasses
import os
import random
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .client import Session
from .config import Config
from .invariants import check
from .logger import get_logger
from .pb import (
    Bootstrap,
    CompressionType,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
    SystemCtx,
    Update,
)
from .raft.peer import Peer
from .raft.quiesce import QuiesceManager
from .raft.read_index import ReadIndex as _DeviceReadIndex
from .raftio import EntryInfo, NodeInfoEvent, SnapshotInfo
from .request import (
    NO_DEADLINE,
    PendingConfigChange,
    PendingLeaderTransfer,
    PendingProposal,
    PendingReadIndex,
    PendingSnapshot,
    RequestState,
    SystemBusy,
    gc_tables,
)
from .rsm.managed import wrap_state_machine
from .rsm.statemachine import (
    ApplyResult,
    SnapshotFileCollection,
    StateMachine,
    Task,
    TaskType,
)
from .statemachine import Result
from .storage.logdb import LogDBLogReader
from .storage.snapshotio import SnapshotReader, _try_snappy

_SYSRAND = random.SystemRandom()

_log = get_logger("nodehost")


class StepInputs:
    """One atomic drain of a node's input queues (see drain_step_inputs)."""

    __slots__ = (
        "received",
        "proposals",
        "read_indexes",
        "config_changes",
        "cc_results",
        "transfers",
        "snapshot_reqs",
        "ticks",
        "gc_ticks",
    )

    def __init__(
        self,
        received=(),
        proposals=(),
        read_indexes=(),
        config_changes=(),
        cc_results=(),
        transfers=(),
        snapshot_reqs=(),
        ticks=0,
        gc_ticks=0,
    ):
        # empty inputs stay the shared () — consumers only iterate and
        # slice, and the idle per-tick drain at 50k rows must not build
        # seven throwaway lists per row
        self.received = list(received) if received else ()
        self.proposals = list(proposals) if proposals else ()
        self.read_indexes = list(read_indexes) if read_indexes else ()
        self.config_changes = list(config_changes) if config_changes else ()
        self.cc_results = list(cc_results) if cc_results else ()
        self.transfers = list(transfers) if transfers else ()
        self.snapshot_reqs = list(snapshot_reqs) if snapshot_reqs else ()
        self.ticks = ticks
        # ticks DROPPED by the add_tick backlog cap: they advance the
        # logical clock (future deadlines are measured on it, so client
        # timeouts stay bounded in wall time during step stalls) but
        # drive no raft ticks
        self.gc_ticks = gc_ticks


class Node:
    # __slots__: a NodeHost hosts tens of thousands of these (reference
    # hosts millions of groups via quiesce [U]); the per-instance dict
    # plus seven deques were the bulk of the r03 112-412 KB/row host
    # footprint.  Queues are plain lists (append + swap-drain only).
    __slots__ = (
        "config", "shard_id", "replica_id", "logdb", "snapshot_storage",
        "transport", "on_leader_updated", "events", "registry",
        "_qlock", "_received", "_proposals", "_read_indexes",
        "_config_changes", "_cc_to_apply", "_snapshot_reqs",
        "_leader_transfers", "_pending_ticks",
        "_ticks_in", "_ticks_taken",
        "pending_proposal", "pending_read_index", "pending_config_change",
        "pending_snapshot", "pending_leader_transfer", "pending_tables",
        "pending_deadline_hint", "device_reads", "hs_lane_slot",
        "tick_count", "leader_id", "proposal_count", "stopped", "stopping",
        "_snapshotting",
        "_applied_since_snapshot", "_retired_snapshots", "_apply_lock",
        "_sm_close_lock", "notify_work", "engine_apply_ready",
        "apply_work_ready",
        "log_reader", "sm", "_stop_event", "peer", "quiesce",
        "wake", "parked_at_tick", "tracer", "_trace_spans",
    )

    def __init__(
        self,
        config: Config,
        initial_members: Dict[int, str],
        join: bool,
        sm_factory: Callable,
        logdb,
        snapshot_storage,
        transport,
        on_leader_updated: Optional[Callable] = None,
        event_listener=None,
        registry=None,
        tracer=None,
    ):
        self.config = config
        self.shard_id = config.shard_id
        self.replica_id = config.replica_id
        # obs/ tracing: None when disabled — every hot-path gate is one
        # attribute load.  _trace_spans maps in-flight entry key ->
        # root span so the step/apply workers can annotate the path;
        # eager (not lazy) when tracing is on, because a lazy create
        # races concurrent producer threads (one fresh dict overwrites
        # the other, losing registrations).  Untraced nodes keep None.
        self.tracer = tracer
        self._trace_spans: Optional[Dict[int, object]] = (
            {} if tracer is not None else None
        )
        self.logdb = logdb
        self.snapshot_storage = snapshot_storage
        self.transport = transport
        self.on_leader_updated = on_leader_updated
        self.events = event_listener
        self.registry = registry

        # --- queues (thread-safe inputs to step) -------------------------
        # plain lists, not deques: producers only append and the drain
        # swaps the whole list out, and an empty deque costs ~750 B — at
        # 50k replica rows the seven deques alone were ~250 MB of idle
        # host footprint
        self._qlock = threading.Lock()
        self._received: list = []  # guarded-by: _qlock
        self._proposals: list = []  # Entry; guarded-by: _qlock
        self._read_indexes: list = []  # SystemCtx; guarded-by: _qlock
        self._config_changes: list = []  # (key, ConfigChange); guarded-by: _qlock
        self._cc_to_apply: list = []  # (ConfigChange|None, accepted); guarded-by: _qlock
        self._snapshot_reqs: list = []  # (key, overhead); guarded-by: _qlock
        self._leader_transfers: list = []  # target; guarded-by: _qlock
        self._pending_ticks = 0  # guarded-by: _qlock
        # single-writer tick lane: the HOST TICKER is the only writer of
        # _ticks_in and the owning step worker the only writer of
        # _ticks_taken, so the per-tick fan-out needs NO lock — at 50k
        # rows the per-node _qlock acquisition in add_tick was the
        # largest single host cost of the r5 scale run (the cap and
        # gc-overflow accounting moved to drain_step_inputs)
        self._ticks_in = 0
        self._ticks_taken = 0

        # --- pending futures --------------------------------------------
        # keys must be unique across NODE INCARNATIONS, not just within
        # one: a restarted replica re-applies its whole log, and if an old
        # in-log entry's key collided with a freshly allocated one, the
        # replayed apply would complete the NEW future — a false ack for a
        # proposal that may never commit (observed as acked-write loss in
        # chaos).  The reference seeds its key generator randomly per
        # start [U]; 47 random bits leave the counter ~2^47 of headroom.
        # request._PendingBase randomizes its own base when none is given;
        # the replica-id salt here additionally makes CROSS-REPLICA
        # distinctness structural (top bits differ by construction, not
        # by luck), closing the ROADMAP cross-replica collision window —
        # ALL five tables get a base, snapshot/transfer included.
        def key_base() -> int:
            # 60 bits (< request.KEY_BASE_BITS): read-index ctx keys must
            # split into two sub-2^31 halves for the device inbox
            # (request.PendingReadIndex.read)
            return ((config.replica_id & 0xFFF) << 48) | _SYSRAND.getrandbits(47)

        _tables_lock = threading.Lock()  # shared: see _PendingBase
        # shared earliest-deadline hint cell: the tick paths (scalar
        # tail below, ops/engine._tick_bookkeeping) probe it lock-free
        # and sweep all five tables under ONE lock acquisition only
        # when the clock reaches it (request.gc_tables)
        self.pending_deadline_hint = [NO_DEADLINE]
        self.pending_proposal = PendingProposal(
            _tables_lock, key_base=key_base(),
            deadline_hint=self.pending_deadline_hint,
        )
        self.pending_read_index = PendingReadIndex(
            _tables_lock, key_base=key_base(),
            deadline_hint=self.pending_deadline_hint,
        )
        self.pending_config_change = PendingConfigChange(
            _tables_lock, key_base=key_base(),
            deadline_hint=self.pending_deadline_hint,
        )
        self.pending_snapshot = PendingSnapshot(
            _tables_lock, key_base=key_base(),
            deadline_hint=self.pending_deadline_hint,
        )
        self.pending_leader_transfer = PendingLeaderTransfer(
            _tables_lock, key_base=key_base(),
            deadline_hint=self.pending_deadline_hint,
        )
        self.pending_tables = (
            self.pending_proposal, self.pending_read_index,
            self.pending_config_change, self.pending_snapshot,
            self.pending_leader_transfer,
        )
        # ctx/quorum table for DEVICE-resident reads (ops/engine.py): the
        # kernel serves the protocol (gate + ctx heartbeats); the host
        # tracks which voters echoed each ctx.  Scalar-path reads use
        # peer.raft.read_index instead — the two never overlap.
        self.device_reads = _DeviceReadIndex()
        # cached hard-state lane slot in this node's LogDB (the ILogDB
        # optional slot protocol; -1 = unresolved).  Resolved once by
        # the device merge tail's first batched lane save; stable for
        # the node's life (the node<->logdb binding never changes).
        self.hs_lane_slot = -1

        self.tick_count = 0
        self.leader_id = 0
        # monotone count of user proposals accepted into the queue
        # (incremented under _qlock beside the enqueue — a bare += on
        # concurrent producer threads is a non-atomic read-modify-write
        # and loses increments); the balance collector diffs it across
        # collect rounds to derive per-shard proposal rates
        self.proposal_count = 0
        self.stopped = False
        # stopping = shutdown announced but SM not yet closed: the node
        # must stop PARTICIPATING (elections, device routing) immediately
        # even though apply workers may still be draining (NodeHost.close
        # sets it on every node before unregistering; a half-closed
        # cluster otherwise keeps electing rows whose hosts are gone)
        self.stopping = False
        self._snapshotting = False
        self._applied_since_snapshot = 0
        # superseded snapshot files are kept for one extra generation: an
        # InstallSnapshot message produced earlier in the SAME step() can
        # still reference the previous file at transport-send time (the
        # payload is read synchronously on this worker; see
        # Transport.send_snapshot)
        self._retired_snapshots: List[str] = []
        # serializes apply() against stop() so the user SM is never closed
        # mid-update
        self._apply_lock = threading.Lock()
        # held for the duration of a streamed snapshot save; stop() takes
        # it before closing the user SM so a save never races the close
        # (applies do NOT take it — saves must not stall the apply path)
        self._sm_close_lock = threading.Lock()
        # set by the engine at registration; wakes the owning step worker
        self.notify_work: Optional[Callable[[], None]] = None
        self.engine_apply_ready: Optional[Callable[[int], None]] = None
        # the apply workers' WorkReady itself (also set at registration):
        # the batched per-SM-worker commit handoff groups wakeups by
        # partition through it (engine._apply_lane_commits) instead of
        # taking the partition lock once per row
        self.apply_work_ready = None

        # --- storage views ----------------------------------------------
        bootstrap = logdb.get_bootstrap_info(config.shard_id, config.replica_id)
        new_node = bootstrap is None
        if new_node:
            # a JOIN may seed the current membership: the bootstrap
            # members were never log entries, so a fresh joiner whose
            # catch-up is snapshot-less (short, uncompacted leader log)
            # replays a log with no trace of them and would believe the
            # shard's voter set is just itself — a leadership transfer
            # to it then self-elects into a split brain (balance-plane
            # finding).  Seeding is safe against the replayed config
            # changes: membership validation no-op-accepts a
            # same-address re-add and rejects removes of absent
            # members, so replay on top of the seeded state converges
            # to the same final membership.  An empty-members join
            # (the reference's contract) still works and learns
            # membership from the leader's snapshot.
            members = dict(initial_members)
            logdb.save_bootstrap_info(
                config.shard_id,
                config.replica_id,
                Bootstrap(addresses=members, join=join),
            )
        else:
            members = dict(bootstrap.addresses)

        self.log_reader, saved_state = LogDBLogReader.from_existing(
            config.shard_id, config.replica_id, logdb
        )
        ss = logdb.get_snapshot(config.shard_id, config.replica_id)

        # --- RSM ---------------------------------------------------------
        managed = wrap_state_machine(sm_factory(config.shard_id, config.replica_id))
        self.sm = StateMachine(
            config.shard_id,
            config.replica_id,
            managed,
            ordered_config_change=config.ordered_config_change,
            is_witness=config.is_witness,
        )
        self._stop_event = threading.Event()
        self.sm.open(self._stop_event)

        membership: Optional[Membership] = None
        if not ss.is_empty():
            if not ss.dummy and not config.is_witness:
                self._recover_sm_from_storage(ss)
            else:
                self.sm.last_applied = max(self.sm.last_applied, ss.index)
            membership = ss.membership
        if membership is None:
            # initial_members are always voters; non-voting/witness replicas
            # enter via config change or join an existing shard
            self.sm.set_initial_membership(dict(members))
            membership = self.sm.get_membership()
        else:
            self.sm.members.restore(membership)
        self._sync_registry(membership)

        # --- raft peer ---------------------------------------------------
        self.peer = Peer.launch(
            config,
            self.log_reader,
            saved_state,
            dict(membership.addresses),
            non_votings=dict(membership.non_votings),
            witnesses=dict(membership.witnesses),
        )
        self.quiesce = QuiesceManager(
            enabled=config.quiesce, election_timeout=config.election_rtt
        )
        # quiesce tick-parking (see NodeHost._ticker_main): a parked
        # node's logical clock freezes; any producer calls wake() to
        # rejoin the active tick set and be granted the elapsed ticks
        self.wake: Optional[Callable[[], None]] = None
        self.parked_at_tick = 0

    # ------------------------------------------------------------------
    # public-API-side entry points (any thread)
    # ------------------------------------------------------------------
    def _wake(self) -> None:
        w = self.wake
        if w is not None:
            w()

    def grant_ticks(self, n: int) -> None:
        """Credit ticks that elapsed while parked (quiesce tick-parking):
        up to one election window becomes raft ticks; the REST IS
        DISCARDED — for this shard, parked time simply did not pass.
        Crediting it to the gc-only clock would jump tick_count past the
        deadline of the very request whose wake granted the ticks
        (review finding: a request to a long-parked shard timed out
        instantly); parking requires no outstanding futures, so no
        deadline needs the parked interval."""
        if n <= 0:
            return
        with self._qlock:
            room = self.config.election_rtt - self._pending_ticks
            self._pending_ticks += min(n, max(0, room))

    def is_parkable(self) -> bool:
        """True when the ticker may park this node: quiesced with no
        queued inputs, no undrained ticks, and NO outstanding request
        futures of any kind — a parked clock never GCs deadlines, so a
        future left pending would block its caller forever (review
        finding: the table must mirror has_work, not just the two hot
        tables).  Lock-free reads — a producer racing in also calls
        wake(), which unparks immediately."""
        # raftlint: ignore[guarded-by] lock-free probe; ticker re-checks under lock
        return (
            self.quiesce.enabled
            and self.quiesce.quiesced
            and not self._pending_ticks
            and self._ticks_in == self._ticks_taken
            and not self._received
            and not self._proposals
            and not self._read_indexes
            and not self._config_changes
            and not self._cc_to_apply
            and not self._snapshot_reqs
            and not self._leader_transfers
            and not self.pending_proposal._pending
            and not self.pending_read_index._pending
            and not self.pending_config_change._pending
            and not self.pending_snapshot._pending
            and not self.pending_leader_transfer._pending
        )

    def add_tick(self) -> None:
        # LOCK-FREE: the host ticker is this counter's only writer (a
        # read-modify-write by a single thread is safe under the GIL);
        # the election-window backlog cap and gc-overflow accounting
        # moved to drain_step_inputs, where the backlog is consumed — at
        # 50k rows the per-node _qlock acquisition here was the largest
        # single host cost of the r5 scale run
        self._ticks_in += 1

    def _trace_register(self, key: int, span) -> None:
        """Associate an in-flight entry key with its root span so the
        step/apply workers can annotate it.  The map is bounded: spans
        of entries that never reach apply (timeouts GC the FUTURE via
        the tick sweep, which ends the span, but nothing pops the key)
        are pruned once ended, with a soft cap behind them.

        Concurrency: producer threads insert here while step/apply
        workers ``pop`` — individual dict ops are GIL-atomic, but
        iterating the live dict is not (a concurrent pop raises
        "changed size during iteration"), so the prune walks a
        ``list(m.items())`` snapshot, which CPython builds without
        dropping the GIL."""
        m = self._trace_spans
        m[key] = span
        if len(m) > 4096:
            items = list(m.items())
            for k, s in items:
                if s.ended:
                    m.pop(k, None)
            # pathological: (almost) all still open — shed the oldest
            # (insertion order) down to 3/4 cap, so the next O(n) scan
            # is ~1k inserts away (amortized, not per-propose)
            overflow = len(m) - 3072
            if len(m) > 4096 and overflow > 0:
                for k, _ in items[:overflow]:
                    m.pop(k, None)

    def propose(
        self, session: Session, cmd: bytes, timeout_ticks: int, span=None
    ) -> RequestState:
        if self.peer.raft.rate_limited():
            # MaxInMemLogSize exceeded: refuse new load until the window
            # drains (reference: ErrSystemBusy on rate limit [U]).
            # Reading inmem.bytes from the API thread is a benign race —
            # it only shifts WHEN the busy signal flips.
            raise SystemBusy("in-memory log over MaxInMemLogSize")
        entry, rs = self.pending_proposal.propose(
            session, cmd, self.tick_count + timeout_ticks
        )
        if span is not None:
            rs.span = span
            span.annotate("request:queued")
            self._trace_register(entry.key, span)
        with self._qlock:
            self.proposal_count += 1
            self._proposals.append(entry)
        self._wake()
        # stop() sets `stopped` BEFORE its drop_all sweep, so a future
        # allocated after the sweep always observes the flag here; one
        # allocated before it was swept already (seal pops-once, so the
        # overlap is benign).  Without this re-check a propose racing
        # stop_shard leaks a table entry no step loop or tick GC will
        # ever complete.
        if self.stopped:
            self.pending_proposal.seal(rs)
        return rs

    def propose_session_op(self, session: Session, timeout_ticks: int) -> RequestState:
        entry, rs = self.pending_proposal.propose(
            session, b"", self.tick_count + timeout_ticks
        )
        with self._qlock:
            self._proposals.append(entry)
        self._wake()
        if self.stopped:
            self.pending_proposal.seal(rs)
        return rs

    def read_index(self, timeout_ticks: int, span=None) -> RequestState:
        ctx, rs = self.pending_read_index.read(self.tick_count + timeout_ticks)
        if span is not None:
            rs.span = span
            span.annotate("request:queued")
        with self._qlock:
            self._read_indexes.append(ctx)
        self._wake()
        if self.stopped:
            self.pending_read_index.seal(rs)
        return rs

    def request_config_change(
        self, cc: ConfigChange, timeout_ticks: int
    ) -> RequestState:
        key, rs = self.pending_config_change.request(
            cc, self.tick_count + timeout_ticks
        )
        with self._qlock:
            self._config_changes.append((key, cc))
        self._wake()
        if self.stopped:
            self.pending_config_change.seal(rs)
        return rs

    def request_snapshot(self, overhead: int, timeout_ticks: int) -> RequestState:
        rs = self.pending_snapshot.request(self.tick_count + timeout_ticks)
        with self._qlock:
            self._snapshot_reqs.append((rs.key, overhead))
        self._wake()
        if self.stopped:
            self.pending_snapshot.seal(rs)
        return rs

    def request_leader_transfer(self, target: int, timeout_ticks: int) -> RequestState:
        rs = self.pending_leader_transfer.request(
            target, self.tick_count + timeout_ticks
        )
        with self._qlock:
            self._leader_transfers.append(target)
        self._wake()
        if self.stopped:
            self.pending_leader_transfer.seal(rs)
        return rs

    def enqueue_received(self, m: Message) -> None:
        if self.stopped:
            return  # a stopped replica drains nothing; don't grow the queue
        with self._qlock:
            self._received.append(m)
        self._wake()

    def enqueue_config_change_result(self, cc, accepted: bool) -> None:
        """Called from the apply worker; consumed by step (single-writer
        raft rule)."""
        with self._qlock:
            self._cc_to_apply.append((cc, accepted))

    def defer_ticks(self, n: int) -> None:
        """Push drained-but-unprocessed ticks back (overload backpressure:
        a step engine whose per-step input capacity is full processes what
        fits and defers the rest; the logical clock lags wall clock
        briefly instead of the row thrashing off the device)."""
        with self._qlock:
            self._pending_ticks += n

    def queued_inputs(self) -> int:
        """Depth of the step input queues (lock-free snapshot; scrape-
        time observability — same benign races as has_work)."""
        # raftlint: ignore[guarded-by] lock-free scrape-time snapshot
        return (
            len(self._received)
            + len(self._proposals)
            + len(self._read_indexes)
            + len(self._config_changes)
            + len(self._cc_to_apply)
            + len(self._snapshot_reqs)
            + len(self._leader_transfers)
        )

    def tick_lag(self) -> int:
        """Ticks granted by the host but not yet consumed by step
        (the engine-backlog signal; lock-free)."""
        # raftlint: ignore[guarded-by] lock-free scrape-time snapshot
        return (self._ticks_in - self._ticks_taken) + self._pending_ticks

    def has_work(self) -> bool:
        # lock-free reads: each container's truthiness/len is atomic
        # under the GIL, and has_work is only ever a HINT (the drain
        # under _qlock is the linearization point) — the colocated
        # coalesce scan calls this once per resident node per launch
        # generation, and the lock acquisition alone was ~60% of a
        # 294 s coalesce bill at 50k rows (SCALE_r05)
        # raftlint: ignore[guarded-by] lock-free hint; drain under _qlock linearizes
        if (
            self._received
            or self._proposals
            or self._read_indexes
            or self._config_changes
            or self._cc_to_apply
            or self._snapshot_reqs
            or self._leader_transfers
            or self._pending_ticks
            or self._ticks_in != self._ticks_taken
        ):
            return True
        return self.peer.has_update()

    # ------------------------------------------------------------------
    # step path (owning step worker only)
    # ------------------------------------------------------------------
    def drain_step_inputs(self) -> "StepInputs":
        """Atomically drain every input queue (the first half of stepNode;
        split out so a vectorized step engine can route drained inputs to
        the device or replay them on the scalar peer — ops/engine.py)."""
        # consume the lock-free ticker lane first (this step worker is
        # _ticks_taken's only writer).  The raft-clock backlog is capped
        # at one election window: a node stalled past that (e.g. behind
        # a one-off XLA compile) must not replay several CheckQuorum/
        # election windows back-to-back with no wall time for responses
        # between them.  Dropped ticks slow only the RAFT clock
        # (liveness-safe); they still advance the logical clock via
        # gc_ticks so pending-future deadlines don't stretch.
        lane = self._ticks_in - self._ticks_taken
        self._ticks_taken += lane
        with self._qlock:
            # swap, don't copy: non-empty queue lists hand over
            # wholesale and fresh empties replace them; empty inputs
            # stay the shared () from StepInputs.__init__
            total = self._pending_ticks + lane
            cap = self.config.election_rtt
            si = StepInputs(
                ticks=min(total, cap),
                gc_ticks=max(0, total - cap),
            )
            if self._received:
                si.received = self._received
                self._received = []
            if self._proposals:
                si.proposals = self._proposals
                self._proposals = []
            if self._read_indexes:
                si.read_indexes = self._read_indexes
                self._read_indexes = []
            if self._config_changes:
                si.config_changes = self._config_changes
                self._config_changes = []
            if self._cc_to_apply:
                si.cc_results = self._cc_to_apply
                self._cc_to_apply = []
            if self._leader_transfers:
                si.transfers = self._leader_transfers
                self._leader_transfers = []
            if self._snapshot_reqs:
                si.snapshot_reqs = self._snapshot_reqs
                self._snapshot_reqs = []
            self._pending_ticks = 0
        return si

    def drain_ticks_only(self, step_cap: int):
        """Consume ONLY the tick inputs — the lock-free ticker lane plus
        the deferred backlog — applying the same two caps as the full
        path (``drain_step_inputs``'s election-window gulp cap, then the
        per-launch ``step_cap`` with defer): one definition so the
        colocated fast tick lane and the full drain can never diverge.

        LOCKING: caller must be the only step consumer (the colocated
        engine's core lock), which serializes it against the OTHER step-
        side ``_pending_ticks`` writers — but NOT against
        ``grant_ticks``, which runs on producer threads under ``_qlock``
        only (NodeHost._wake_node unparking a quiesced node).  Any
        ``_pending_ticks`` read-modify-write therefore takes ``_qlock``;
        without it a node woken concurrently with a fast-lane step could
        lose up to an election window of credited ticks.

        FAST PATH (lock-free): when the deferred backlog reads 0 and the
        drained lane needs no defer, ``_pending_ticks`` is never
        written, so there is no RMW to order against ``grant_ticks`` —
        a grant racing the read simply stays queued for the next drain
        (the exact guarantee the locked path gives a grant arriving one
        instruction later).  This is the common shape of every fast-lane
        step, and at 250k resident rows the per-row ``_qlock``
        acquisition here was the single largest fast-lane cost left
        after the r6 host-plane vectorization (same finding as
        ``add_tick``'s lock elision at r5 scale).  Returns
        ``(ticks, gc_ticks)``."""
        lane = self._ticks_in - self._ticks_taken
        self._ticks_taken += lane
        if step_cap < 1:
            step_cap = 1
        # raftlint: ignore[guarded-by] lock-free backlog probe; non-zero falls to the locked path
        if not self._pending_ticks:
            cap = self.config.election_rtt
            ticks = lane if lane < cap else cap
            gc = lane - ticks
            if ticks <= step_cap:
                return ticks, gc
            with self._qlock:
                self._pending_ticks += ticks - step_cap
            return step_cap, gc
        with self._qlock:
            total = self._pending_ticks + lane
            ticks = min(total, self.config.election_rtt)
            gc = total - ticks
            if ticks > step_cap:
                self._pending_ticks = ticks - step_cap
                ticks = step_cap
            else:
                self._pending_ticks = 0
        return ticks, gc

    def step(self) -> Optional[Update]:
        """Drain inputs into the raft peer and produce this shard's Update
        (reference: node.stepNode [U])."""
        if self.stopped:
            return None
        return self.step_with_inputs(self.drain_step_inputs())

    def step_with_inputs(self, si: "StepInputs") -> Optional[Update]:
        """Run the scalar step on pre-drained inputs."""
        received = si.received
        proposals = si.proposals
        read_indexes = si.read_indexes
        config_changes = si.config_changes
        cc_results = si.cc_results
        transfers = si.transfers
        snapshot_reqs = si.snapshot_reqs
        ticks = si.ticks
        # cap ticks per step at half an election window: the reference's
        # ticker delivers ticks ONE at a time interleaved with message
        # processing [U]; our batched drain would otherwise gulp several
        # CheckQuorum/election windows in one step with zero wall time
        # for responses to arrive — a healthy leader would step itself
        # down.  Excess ticks are deferred (has_work re-arms the worker).
        cap = max(1, self.peer.raft.election_timeout // 2)
        if ticks > cap:
            self.defer_ticks(ticks - cap)
            si.ticks = ticks = cap

        # config-change application results from the apply loop
        for cc, accepted in cc_results:
            if accepted and cc is not None:
                self.peer.apply_config_change(cc)
            else:
                self.peer.reject_config_change()

        # activity-based quiesce exit / peer enter-hints
        if self.quiesce.enabled:
            for m in received:
                if m.type == MessageType.QUIESCE:
                    # no-leader gate (QuiesceManager.tick block=): never
                    # join a peer's quiesce while leaderless — parking a
                    # shard mid-election freezes the churn that would
                    # produce the leader
                    if self.peer.raft.leader_id:
                        self.quiesce.quiesce_hint()
                elif self.quiesce.record_activity(m.type):
                    self._poke_peers_out_of_quiesce()
            if proposals or read_indexes or config_changes or transfers:
                if self.quiesce.record_activity(MessageType.PROPOSE):
                    self._poke_peers_out_of_quiesce()

        # received-snapshot files are saved by the chunk sink before raft
        # decides; any install this step that raft does NOT accept must be
        # deleted or its rx file leaks forever (code-review finding)
        rx_candidates = [
            m.snapshot.filepath
            for m in received
            if m.type == MessageType.INSTALL_SNAPSHOT and m.snapshot.filepath
        ]

        tracer = self.tracer
        if tracer is None:
            for m in received:
                self.peer.handle(m)
        else:
            for m in received:
                if m.trace_id:
                    # follower side of a traced replicate: parent the
                    # append span to the leader's proposal span carried
                    # in the message — the cross-host stitch
                    fs = tracer.start_span(
                        "follower:append", m.trace_id, m.span_id,
                        shard_id=self.shard_id,
                    )
                    fs.annotate(
                        f"recv:{m.type.name} from={m.from_} "
                        f"entries={len(m.entries)}"
                    )
                    self.peer.handle(m)
                    fs.end()
                else:
                    self.peer.handle(m)

        if proposals:
            ts = self._trace_spans
            if ts:
                for e in proposals:
                    s = ts.get(e.key)
                    if s is not None:
                        s.annotate(f"step:proposed batch={len(proposals)}")
            self.peer.propose_entries(proposals)
        for key, cc in config_changes:
            self.peer.propose_config_change(cc, key)
        for ctx in read_indexes:
            self.peer.read_index(ctx)
        for target in transfers:
            self.peer.request_leader_transfer(target)
        for key, overhead in snapshot_reqs:
            self._save_snapshot_request(key, overhead)

        for _ in range(ticks):
            self.tick_count += 1
            was_quiesced = self.quiesce.quiesced
            if self.quiesce.tick(
                busy=self.peer.raft.catching_up_peers(),
                block=self.peer.raft.leader_id == 0,
            ):
                if not was_quiesced:  # newly entered: drag peers along
                    self.broadcast_quiesce_enter()
                self.peer.quiesced_tick()
            else:
                self.peer.tick()
            # tick-driven GC of timed-out futures: hint-gated — one
            # int compare per tick, a five-table single-lock sweep
            # only when the clock reaches the earliest pending
            # deadline (request.gc_tables keeps the timeout-delivery
            # tick exactly what the old sweep-every-tick loop gave)
            gc_tables(
                self.pending_tables, self.pending_deadline_hint,
                self.tick_count,
            )
        if si.gc_ticks:
            # backlog-dropped ticks: clock + deadline GC only (deadlines
            # are monotone, so one pass at the final count is exact)
            self.tick_count += si.gc_ticks
            gc_tables(
                self.pending_tables, self.pending_deadline_hint,
                self.tick_count,
            )

        self._check_leader_change()

        if not self.peer.has_update():
            for path in rx_candidates:  # every install was rejected
                self.snapshot_storage.remove(path)
            return None
        u = self.peer.get_update(last_applied=self.sm.last_applied)
        accepted_path = u.snapshot.filepath if not u.snapshot.is_empty() else None
        for path in rx_candidates:
            if path != accepted_path:
                self.snapshot_storage.remove(path)
        self.dispatch_dropped(u)
        return u

    def _trace_update(self, u: Update) -> None:
        """Annotate traced proposals along the raft path of one Update
        (step worker only) and stamp outbound REPLICATEs with trace
        context so the follower-side append spans stitch in.  Runs
        BEFORE process_update's send/persist so the stamped messages
        are what the transport actually carries."""
        # lookups are gated on APPLICATION entries: config-change keys
        # come from an INDEPENDENT sequential counter (request.py
        # _PendingBase) and collide with proposal keys — an ungated
        # ts.get would annotate (and stamp) the wrong span
        ts = self._trace_spans
        app = EntryType.APPLICATION
        for e in u.entries_to_save:
            if e.type != app:
                continue
            s = ts.get(e.key)
            if s is not None:
                s.annotate(f"raft:append index={e.index} term={e.term}")
        msgs = u.messages
        for i, m in enumerate(msgs):
            if m.type != MessageType.REPLICATE or not m.entries:
                continue
            for e in m.entries:
                if e.type != app:
                    continue
                s = ts.get(e.key)
                if s is not None:
                    msgs[i] = dataclasses.replace(
                        m, trace_id=s.trace_id, span_id=s.span_id
                    )
                    s.annotate(
                        f"raft:replicate to={m.to} entries={len(m.entries)}"
                    )
                    break
        for e in u.committed_entries:
            if e.type != app:
                continue
            s = ts.get(e.key)
            if s is not None:
                s.annotate(f"raft:committed index={e.index}")

    def _trace_committed(self, entries) -> None:
        """The committed leg of ``_trace_update`` alone, for the device
        merge tail's LANE rows (ops/engine.py): their commit advances
        carry no ``Update`` object, so the per-entry span annotation
        must ride the lane handoff directly.  Called only when
        ``_trace_spans`` is non-empty."""
        ts = self._trace_spans
        app = EntryType.APPLICATION
        for e in entries:
            if e.type != app:
                continue
            s = ts.get(e.key)
            if s is not None:
                s.annotate(f"raft:committed index={e.index}")

    def dispatch_dropped(self, u: Update) -> None:
        """Fail dropped-request futures fast (both step engines call this)."""
        ts = self._trace_spans
        if ts:
            for e in u.dropped_entries:
                # APPLICATION only: a config-change key colliding with a
                # live proposal key must not evict the proposal's span
                if e.type == EntryType.APPLICATION:
                    ts.pop(e.key, None)  # notify(DROPPED) ends the span
        for e in u.dropped_entries:
            # route by entry kind: proposal and config-change futures live
            # in different tables with independent key spaces
            if e.type == EntryType.CONFIG_CHANGE:
                # transient (no leader), not a membership-validation reject:
                # clients should retry
                self.pending_config_change.dropped(e.key)
            else:
                self.pending_proposal.dropped(e.key)
        for ctx in u.dropped_read_indexes:
            self.pending_read_index.dropped(ctx)

    def _sync_registry(self, membership: Membership) -> None:
        """Every replica (not just the API caller) must be able to resolve
        every member's address."""
        if self.registry is None:
            return
        for group in (
            membership.addresses,
            membership.non_votings,
            membership.witnesses,
        ):
            for pid, addr in group.items():
                if addr:
                    self.registry.add(self.shard_id, pid, addr)

    def _poke_peers_out_of_quiesce(self) -> None:
        # only the leader needs to poke (resume heartbeats, which reset
        # follower election timers); a woken follower's real traffic
        # (forwarded proposal, vote, replicate) wakes peers by itself
        if self.peer.is_leader():
            self.peer.raft.handle(Message(type=MessageType.LEADER_HEARTBEAT))

    def broadcast_wake(self) -> None:
        """Host-path quiesce-exit poke to every peer.  LEADER_HEARTBEAT
        is 'activity' to the quiesce manager and a no-op to follower
        raft, and mere DELIVERY unparks the peer's host node
        (enqueue_received -> wake), so its election clock runs again —
        the transport leg is what matters, not the payload."""
        for pid in sorted(self.peer.raft.addresses):
            if pid == self.replica_id:
                continue
            self.transport.send(
                Message(
                    type=MessageType.LEADER_HEARTBEAT,
                    to=pid,
                    from_=self.replica_id,
                    shard_id=self.shard_id,
                )
            )

    def broadcast_quiesce_enter(self) -> None:
        """Announce entering quiesce so peers join promptly (reference:
        pb.Quiesce [U]) — staggered entry would leave the leader
        heartbeating at already-quiesced followers."""
        for pid in sorted(self.peer.raft.addresses):
            if pid == self.replica_id:
                continue
            self.transport.send(
                Message(
                    type=MessageType.QUIESCE,
                    to=pid,
                    from_=self.replica_id,
                    shard_id=self.shard_id,
                )
            )

    def _check_leader_change(self) -> None:
        lid = self.peer.leader_id()
        if lid != self.leader_id:
            self.leader_id = lid
            if lid != 0:
                self.pending_leader_transfer.notify_leader(lid)
            elif self.quiesce.enabled and (
                self.quiesce.quiesced or self.quiesce.exit_grace > 0
            ):
                # the shard went LEADERLESS while (or right after)
                # being quiesced — the dead-leader-of-an-idle-shard
                # case.  Peer replicas may still be tick-PARKED on
                # their hosts with a stale leader view: parked clocks
                # never fire election timeouts, and device-routed
                # pre-votes alone do not unpark them, so without a
                # host-path poke the shard stays leaderless forever
                # (churn-audit finding: a quiesced 500-shard cluster
                # never re-elected after a leader kill).
                self.broadcast_wake()
            if self.on_leader_updated is not None:
                self.on_leader_updated(
                    self.shard_id, self.replica_id, self.peer.term(), lid
                )

    # ------------------------------------------------------------------
    # post-save processing (owning step worker; logdb write already done)
    # ------------------------------------------------------------------
    def process_update(self, u: Update) -> bool:
        """reference: node.processRaftUpdate + commitRaftUpdate [U].
        Returns True if apply work was scheduled."""
        if self._trace_spans:
            self._trace_update(u)
        scheduled = False
        if not u.snapshot.is_empty():
            self._install_snapshot(u.snapshot)
            # the queued SNAPSHOT_RECOVER task needs the apply worker
            # NOW: an install with no trailing committed entries (a
            # fully-compacted leader log and a quiet shard — the normal
            # big-state catch-up shape) otherwise sits unrecovered until
            # unrelated traffic schedules an apply, and a quiet follower
            # stays at applied=0 forever while the leader believes it
            # caught up (found by the bigstate TCP verify drive)
            scheduled = True
        if u.entries_to_save:
            ents = u.entries_to_save
            check(
                all(
                    ents[i].index + 1 == ents[i + 1].index
                    for i in range(len(ents) - 1)
                ),
                "entries_to_save not contiguous: %s",
                [e.index for e in ents[:8]],
            )
            check(
                u.state.is_empty() or u.state.commit <= ents[-1].index
                or u.state.commit <= self.log_reader.last_index()
                or not u.snapshot.is_empty(),
                "hard-state commit %d beyond save window",
                u.state.commit,
            )
            self.log_reader.append(u.entries_to_save)
        for m in u.messages:
            self.transport.send(m)
        if u.ready_to_reads:
            for rtr in u.ready_to_reads:
                self.pending_read_index.confirmed(rtr.system_ctx, rtr.index)
            # the read index may already be applied (idle shard): complete now
            self.pending_read_index.applied(self.sm.last_applied)
        if u.committed_entries:
            self.sm.task_queue.add(
                Task(type=TaskType.ENTRIES, entries=u.committed_entries)
            )
            scheduled = True
        self.peer.commit(u)
        return scheduled

    def _install_snapshot(self, ss: Snapshot) -> None:
        """A received snapshot reached the log (InstallSnapshot accepted)."""
        self.log_reader.apply_snapshot(ss)
        self.sm.task_queue.add(Task(type=TaskType.SNAPSHOT_RECOVER, snapshot=ss))

    # ------------------------------------------------------------------
    # apply path (owning apply worker only)
    # ------------------------------------------------------------------
    def apply(self) -> None:
        """Drain the task queue through the RSM (reference:
        engine applyWorkerMain -> rsm Handle [U])."""
        with self._apply_lock:
            if self.stopped:
                return
            self._apply_locked()

    def _apply_locked(self) -> None:
        for task in self.sm.task_queue.get_all():
            if task.type == TaskType.ENTRIES:
                results = self.sm.handle(task)
                self._complete_applied(results)
                self._applied_since_snapshot += len(task.entries)
            elif task.type == TaskType.SNAPSHOT_RECOVER:
                self._recover_from_snapshot(task.snapshot)
        self.pending_read_index.applied(self.sm.last_applied)
        self.peer.notify_raft_last_applied(self.sm.last_applied)
        if (
            self.config.snapshot_entries > 0
            and self._applied_since_snapshot >= self.config.snapshot_entries
        ):
            self._applied_since_snapshot = 0
            with self._qlock:
                self._snapshot_reqs.append((0, self.config.compaction_overhead))

    def _complete_applied(self, results: List[ApplyResult]) -> None:
        for r in results:
            e = r.entry
            if r.config_change is not None or (
                e.type == EntryType.CONFIG_CHANGE
            ):
                self.enqueue_config_change_result(r.config_change, not r.rejected)
                if not r.rejected and r.config_change is not None:
                    cc = r.config_change
                    if self.registry is not None:
                        if cc.type == ConfigChangeType.REMOVE_REPLICA:
                            self.registry.remove(self.shard_id, cc.replica_id)
                        elif cc.address:
                            self.registry.add(
                                self.shard_id, cc.replica_id, cc.address
                            )
                if self.notify_work is not None:
                    self.notify_work()
                self.pending_config_change.applied(e.key, r.rejected)
                if self.events is not None and not r.rejected:
                    self.events.membership_changed(
                        NodeInfoEvent(self.shard_id, self.replica_id)
                    )
            elif e.key:
                ts = self._trace_spans
                if ts:
                    # NOT popped at apply: a REPLICATE re-sent to a
                    # lagging/healed follower AFTER the leader applied
                    # must still find the span so it carries real trace
                    # context and the follower's append leg stitches
                    # into the merged timeline (the ROADMAP obs gap —
                    # safe since PR 5's randomized per-table key bases
                    # shrank cross-replica key collisions to ~2^-47).
                    # Ended entries are evicted by the _trace_register
                    # prune amortizer, which bounds the map.
                    s = ts.get(e.key)
                    if s is not None:
                        s.annotate(
                            f"rsm:applied index={e.index}"
                            f"{' rejected' if r.rejected else ''}"
                        )
                self.pending_proposal.applied(e.key, r.result, r.rejected)

    # ------------------------------------------------------------------
    # device-resident reads (the engine's ReadIndex hot path)
    # ------------------------------------------------------------------
    def handle_device_read_resp(self, m: Message) -> None:
        """Synthetic READ_INDEX_RESP-to-self emitted by the device kernel
        (ops/kernel._handle_read_index): reject -> drop; log_index==0 ->
        request recorded at index=m.commit; log_index==K -> voter K
        confirmed the ctx.  Quorum tracking is host-side because the SoA
        state has no per-ctx table; correctness only needs the count of
        DISTINCT voters that echoed the ctx, which is what device_reads
        accumulates (reference: internal/raft/readindex.go [U])."""
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        if m.reject:
            self.device_reads.drop(ctx)
            self.pending_read_index.dropped(ctx)
            return
        if m.log_index == 0:
            if self.peer.raft.quorum() <= 1:
                self.pending_read_index.confirmed(ctx, m.commit)
                self.pending_read_index.applied(self.sm.last_applied)
            else:
                self.device_reads.add_request(m.commit, ctx, 0)
            return
        done = self.device_reads.confirm(
            ctx, m.log_index, self.peer.raft.quorum()
        )
        if done:
            for s in done:
                self.pending_read_index.confirmed(s.ctx, s.index)
            self.pending_read_index.applied(self.sm.last_applied)

    def drop_device_reads(self) -> None:
        """Leadership lost / row left the device: fail pending device
        reads so clients retry (mirrors Raft.drop_pending_read_indexes)."""
        for low, high in list(self.device_reads.queue):
            self.pending_read_index.dropped(SystemCtx(low=low, high=high))
        self.device_reads.clear()

    def _recover_sm_from_storage(self, ss: Snapshot) -> None:
        """Open the v2 container and restore the SM + sessions +
        membership through it, resolving external files to absolute
        paths in the snapshot dir (reference: rsm recover +
        ISnapshotFileCollection restore [U])."""
        f = self.snapshot_storage.open_read(ss.filepath)
        try:
            reader = SnapshotReader(f)
            files = [
                dataclasses.replace(
                    sf,
                    filepath=self.snapshot_storage.external_path(
                        ss.filepath, sf.filepath
                    ),
                )
                for sf in reader.external_files
            ]
            for sf in files:
                if not os.path.exists(sf.filepath):
                    raise IOError(
                        f"snapshot external file missing: {sf.filepath}"
                    )
            self.sm.recover_from_snapshot_stream(reader, files)
        finally:
            f.close()

    def _recover_from_snapshot(self, ss: Snapshot) -> None:
        if ss.dummy or self.config.is_witness:
            self.sm.last_applied = max(self.sm.last_applied, ss.index)
            self.sm.members.restore(ss.membership)
            return
        try:
            self._recover_sm_from_storage(ss)
        except Exception as e:  # noqa: BLE001 — any load/decode failure
            # the raft log was already reset to ss.index; applying anything
            # past it without this state would silently diverge — halt the
            # replica loudly instead (reference: dragonboat panics on
            # snapshot recovery failure [U])
            _log.critical(
                "[%d:%d] FATAL: snapshot %d unrecoverable (%s); halting replica",
                self.shard_id,
                self.replica_id,
                ss.index,
                e,
            )
            self.stopped = True
            raise
        self._sync_registry(ss.membership)
        if self.events is not None:
            self.events.snapshot_recovered(
                SnapshotInfo(self.shard_id, self.replica_id, ss.replica_id, ss.index)
            )

    # ------------------------------------------------------------------
    # snapshotting (step-worker context for now; dedicated workers later)
    # ------------------------------------------------------------------
    def _snapshot_compression(self):
        """The per-block codec recorded in the container AND in the
        Snapshot meta (reference: SnapshotCompression config [U]).
        Compression now lives INSIDE the v2 container (per block, self-
        describing), so cross-host recovery never depends on out-of-band
        metadata surviving the chunk lane."""
        want = CompressionType(self.config.snapshot_compression)
        if want == CompressionType.SNAPPY and _try_snappy() is None:
            return CompressionType.ZLIB  # meta records what is actually used
        return want

    def _save_snapshot_request(self, key: int, overhead: int) -> None:
        """Save a snapshot of the current applied state and compact the log
        (reference: rsm.SaveSnapshot + snapshotter [U])."""
        if self._snapshotting:
            if key:
                self.pending_snapshot.done(key, 0, failed=True)
            return
        self._snapshotting = True
        try:
            with self._apply_lock:
                if self.stopped:
                    if key:
                        self.pending_snapshot.done(key, 0, failed=True)
                    return
                index = self.sm.last_applied
                prev = self.logdb.get_snapshot(self.shard_id, self.replica_id)
                if index == 0 or prev.index >= index:
                    if key:
                        self.pending_snapshot.done(key, 0, failed=True)
                    return
                compression = self._snapshot_compression()

            def build(fileobj, copy_fn):
                coll = SnapshotFileCollection(copy_fn)
                # the SM streams through the v2 block writer with
                # bounded memory (storage/snapshotio.py); external
                # files are staged beside the container by copy_fn
                return self.sm.save_snapshot_stream(
                    fileobj,
                    coll,
                    compression=int(compression),
                )

            # the streamed save runs OUTSIDE _apply_lock so a long
            # disk write never stalls the apply pipeline: regular SMs
            # serialize under rsm._mu anyway, concurrent/on-disk SMs
            # prepare under it and stream concurrently (reference: rsm
            # concurrent snapshot [U]).  _sm_close_lock only excludes
            # stop() closing the user SM mid-save.  The container's
            # index is captured under rsm._mu inside build; the dir is
            # named from that result, so name and content agree even
            # when applies advance past the pre-check index.
            with self._sm_close_lock:
                if self.stopped:
                    if key:
                        self.pending_snapshot.done(key, 0, failed=True)
                    return
                filepath, (index, term, _files) = (
                    self.snapshot_storage.save_stream(
                        self.shard_id,
                        self.replica_id,
                        index,
                        build,
                        index_from_result=lambda res: res[0],
                    )
                )
            ss = Snapshot(
                filepath=filepath,
                file_size=self.snapshot_storage.file_size(filepath),
                index=index,
                term=term,
                membership=self.sm.get_membership(),
                shard_id=self.shard_id,
                replica_id=self.replica_id,
                compression=compression,
            )
            u = Update(
                shard_id=self.shard_id, replica_id=self.replica_id, snapshot=ss
            )
            self.logdb.save_snapshots([u])
            # the reader must know the snapshot so the leader can stream it
            # to followers that fall behind the compaction point
            self.log_reader.create_snapshot(ss)
            compact_to = max(0, index - max(overhead, 0))
            if compact_to > 0:
                # compact the reader first: it snapshots the boundary term
                # while the entry is still readable in the logdb
                self.log_reader.compact(compact_to)
                self.logdb.remove_entries_to(
                    self.shard_id, self.replica_id, compact_to
                )
            if not prev.is_empty():
                self._retired_snapshots.append(prev.filepath)
                self._gc_retired_snapshots()
            if key:
                self.pending_snapshot.done(key, index)
            if self.events is not None:
                self.events.snapshot_created(
                    SnapshotInfo(self.shard_id, self.replica_id, 0, index)
                )
                if compact_to > 0:
                    self.events.log_compacted(
                        EntryInfo(self.shard_id, self.replica_id, compact_to)
                    )
        finally:
            self._snapshotting = False

    def _gc_retired_snapshots(self) -> None:
        """Delete superseded snapshot files, keeping the newest retiree one
        generation longer (see the field comment)."""
        for p in self._retired_snapshots[:-1]:
            self.snapshot_storage.remove(p)
        del self._retired_snapshots[:-1]

    # ------------------------------------------------------------------
    # leader-lease reads (gateway/ front plane; docs/GATEWAY.md)
    # ------------------------------------------------------------------
    def lease_remaining_ticks(self) -> int:
        """Ticks of CheckQuorum leader lease left, or 0 when no lease.

        The lease argument (docs/GATEWAY.md "Lease-read safety"): with
        ``check_quorum`` on, every follower refuses to grant votes while
        it heard from a live leader within its own election window
        (``Raft._in_lease``), so no challenger can be elected until one
        full election window after a majority last heard from us; the
        leader renews the lease on every quorum of replicate/heartbeat
        responses (``Raft.lease_remaining_ticks`` over the remotes'
        ``last_resp_tick``), so a healthy leader holds it continuously
        instead of saw-toothing with the check-quorum boundary.
        Serving a local read additionally requires (same as ReadIndex
        serving):

        * a committed entry in the CURRENT term (a fresh leader's
          commit index is not yet proven current);
        * ``last_applied`` caught up to the local commit index, so the
          lookup observes every entry this leader committed.

        Callers keep a safety margin (ticks are per-host logical
        clocks; the hosts' tickers drift) — see
        ``NodeHost.try_lease_read``.  Lock-free probe off producer
        threads: every field read is one GIL-atomic load, and a lease
        lost immediately after a True answer is exactly the race the
        margin exists for."""
        if self.stopped or self.stopping:
            return 0
        r = self.peer.raft
        if not r.check_quorum or not self.peer.is_leader():
            return 0
        try:
            if not r.committed_entry_in_current_term():
                return 0
            if self.sm.last_applied < r.log.committed:
                return 0
            # inside the guard too: it copies the membership dicts,
            # which a concurrently-applying config change mutates
            # (review finding — "dictionary changed size" would crash
            # a metrics scrape)
            return r.lease_remaining_ticks()
        except Exception:  # noqa: BLE001 — racing a concurrent step's
            # log/membership mutation (compaction/append/config
            # change): no lease this probe
            return 0

    def lease_held(self, margin_ticks: int = 2) -> bool:
        """True when the CheckQuorum lease has more than ``margin_ticks``
        left — the gateway's fast-read gate."""
        return self.lease_remaining_ticks() > margin_ticks

    def bounded_read_probe(self, bound_ticks: int) -> tuple:
        """BOUNDED_STALENESS serving gate (readplane/,
        docs/READPLANE.md): returns ``(ok, applied_index,
        staleness_ticks)``.  ``ok`` means this replica may serve a
        local read stamped ``staleness_ticks`` stale without exceeding
        ``bound_ticks``:

        * a leader serves at staleness 0 (its state is current);
        * a follower serves iff it has a leader, heard from it within
          ``bound_ticks`` (``election_tick`` resets on leader traffic),
          AND has applied everything up to the leader's last-known
          UNCAPPED commit (``Raft.leader_commit_hint``) — fresh
          heartbeats alone must not let a still-recovering replica
          serve arbitrarily old state as "bounded".

        Lock-free probe off producer threads, same contract as
        ``lease_remaining_ticks``: every read is one GIL-atomic load
        and a state change right after a True answer is absorbed by the
        bound itself (the stamp is conservative — staleness can only
        have been SMALLER when the fields were loaded)."""
        if self.stopped or self.stopping:
            return False, 0, 0
        r = self.peer.raft
        applied = self.sm.last_applied
        try:
            if self.peer.is_leader():
                return True, applied, 0
            if r.leader_id == 0:
                return False, applied, bound_ticks + 1
            staleness = r.election_tick
            if staleness > bound_ticks:
                return False, applied, staleness
            if applied < r.leader_commit_hint:
                return False, applied, staleness
            return True, applied, staleness
        except Exception:  # noqa: BLE001 — racing a concurrent step's
            # mutation (same guard as lease_remaining_ticks): shed this
            # probe rather than serve on torn state
            return False, applied, bound_ticks + 1

    # ------------------------------------------------------------------
    def get_membership(self) -> Membership:
        return self.sm.get_membership()

    def lookup(self, query):
        return self.sm.lookup(query)

    def stale_read(self, query):
        return self.sm.lookup(query)

    def stop(self) -> None:
        self.stopping = True
        self.stopped = True
        self._stop_event.set()
        self.pending_proposal.drop_all()
        self.pending_read_index.drop_all()
        self.pending_config_change.drop_all()
        self.pending_snapshot.drop_all()
        self.pending_leader_transfer.drop_all()
        # retired files can't be referenced once this replica is down
        # (receivers own their streamed copies); reclaim them so restarts
        # don't orphan files
        for p in self._retired_snapshots:
            self.snapshot_storage.remove(p)
        self._retired_snapshots = []
        # wait for any in-flight apply before closing the user SM
        with self._apply_lock, self._sm_close_lock:
            self.sm.managed.close()
