"""NodeHost: the process-level host multiplexing many raft shards.

reference: nodehost.go [U].  One NodeHost owns the engine, transport,
LogDB, registry and ticker; shards are started/stopped dynamically and all
public request APIs (SyncPropose/SyncRead/membership/snapshot/transfer)
live here.
"""
from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Callable, Dict, Optional

from .client import Session
from .config import Config, ConfigError, NodeHostConfig
from .engine.execengine import ExecEngine
from .events import EventFanout
from .logger import get_logger
from .metrics import MetricsRegistry
from .node import Node
from .obs.trace import UNSAMPLED
from .pb import (
    ConfigChange,
    ConfigChangeType,
    Membership,
    MessageBatch,
    MessageType,
)
from .pb import Message
from .raftio import LeaderInfo, NodeInfoEvent
from .readplane import (
    BOUND_TICKS_DEFAULT,
    Consistency,
    ReadResult,
    StaleBoundExceeded,
)
from .request import (
    RequestError,
    RequestResultCode,
    RequestState,
    ShardNotFound,
    SystemBusy,
)
from .statemachine import Result
from .storage.snapshotter import FileSnapshotStorage
from .transport import InProcTransport, Registry, Transport
from .transport.chunk import ChunkSink

_log = get_logger("nodehost")


class NodeHostClosed(RequestError):
    pass


class TimeoutError_(RequestError):
    pass


class RequestRejected(RequestError):
    pass


class RequestDropped(RequestError):
    pass


class RequestTerminated(RequestError):
    pass


_CODE_ERRORS = {
    RequestResultCode.TIMEOUT: TimeoutError_,
    RequestResultCode.REJECTED: RequestRejected,
    RequestResultCode.DROPPED: RequestDropped,
    RequestResultCode.TERMINATED: RequestTerminated,
    RequestResultCode.ABORTED: RequestTerminated,
}


def _check(code: RequestResultCode, rs: RequestState) -> Result:
    if code == RequestResultCode.COMPLETED:
        return rs.result
    raise _CODE_ERRORS.get(code, RequestError)(code.name)


class NodeHost:
    def __init__(self, config: NodeHostConfig):
        config.validate()
        self.config = config
        # process-identity timestamp the fleet scope reports in every
        # obs reply: a collector cross-checks uptime against its
        # epoch-based restart detection (docs/OBSERVABILITY.md)
        self._started_mono = time.monotonic()
        # shard_id -> node (one replica/shard); guarded-by: _nodes_lock
        self._nodes: Dict[int, Node] = {}
        # quiesce tick-parking: quiesced-idle nodes leave the active
        # tick set entirely (their logical clocks freeze) and rejoin via
        # node.wake() when any producer touches them — the host-side
        # analogue of the reference's 'millions of idle groups cost ~0'
        # (quiesce + workReady [U]); at 50k rows the flat per-tick
        # fan-out alone was ~1M lock-ops/sec of pure Python
        self._parked: Dict[int, Node] = {}  # shard_id -> parked node; guarded-by: _nodes_lock
        self._global_ticks = 0
        self._nodes_lock = threading.RLock()
        self._closed = False

        # exclusive dir lock + deployment-id check (reference:
        # internal/server environment [U])
        from .env import Env

        self._env = Env(config.nodehost_dir, config.deployment_id)

        try:

            expert = config.expert
            if expert.logdb_factory:
                self.logdb = expert.logdb_factory(config)
            else:
                # durable by default, like the reference (tan is its v4
                # default LogDB [U]); volatile storage is opt-in via
                # storage.logdb.in_mem_logdb_factory
                from .storage.tan import tan_logdb_factory

                self.logdb = tan_logdb_factory(config)
            if expert.snapshot_storage_factory:
                self.snapshot_storage = expert.snapshot_storage_factory(config)
            else:
                # snapshots are durable by default, rooted in the nodehost dir
                # (reference: snapshot dirs under NodeHostDir [U])
                import os

                self.snapshot_storage = FileSnapshotStorage(
                    os.path.join(config.nodehost_dir, "snapshots")
                )
            self.gossip: Optional[object] = None
            if config.address_by_nodehost_id:
                from .id import get_nodehost_id
                from .transport.gossip import GossipManager, GossipRegistry

                self.nodehost_id = get_nodehost_id(config.nodehost_dir)
                self.gossip = GossipManager(
                    self.nodehost_id,
                    config.raft_address,
                    config.gossip.bind_address,
                    list(config.gossip.seed),
                    advertise_address=config.gossip.advertise_address,
                )
                self.gossip.start()
                self.registry = GossipRegistry(self.gossip)
            else:
                self.registry = Registry()
            # metrics exist before everything that registers series
            # (event fanout, per-target breakers, the engine)
            self.metrics = MetricsRegistry(enabled=config.enable_metrics)
            # readplane per-path read counters (docs/READPLANE.md).
            # Plain dict bumps: observability only, and a GIL-preempted
            # lost increment is the same benign race every other scrape
            # surface here accepts — no lock on the read hot paths.
            self._read_paths: Dict[str, int] = {
                "lease": 0, "read_index": 0, "follower": 0,
                "bounded": 0, "bounded_shed": 0,
            }
            # pre-resolved labeled counters: counter() takes the
            # registry lock; resolving once keeps the per-read cost at
            # one dict load + one GIL-atomic add
            self._read_counters = {
                p: self.metrics.counter("nodehost_read_total", {"path": p})
                for p in self._read_paths
            }
            # observability (obs/, docs/OBSERVABILITY.md): both gates
            # default off and leave the attribute None — every hot-path
            # check is one attribute load
            from .obs import FlightRecorder, Tracer

            self.tracer = (
                Tracer(
                    host=config.raft_address,
                    sample_rate=config.trace_sample_rate,
                )
                if config.enable_tracing
                else None
            )
            self.recorder = (
                FlightRecorder(host=config.raft_address)
                if config.enable_flight_recorder
                else None
            )
            self.events = EventFanout(
                config.raft_event_listener,
                config.system_event_listener,
                metrics=self.metrics,
                tap=self._recorder_tap if self.recorder is not None else None,
            )

            # received snapshots get a unique suffix: re-streams of the same
            # index must never clobber a file a queued recover task still wants
            self._rx_snapshot_seq = itertools.count(1)
            self._chunk_sink = ChunkSink(
                begin_fn=lambda s, r, i: self.snapshot_storage.begin_receive(
                    s, r, i, suffix=f"rx{next(self._rx_snapshot_seq)}"
                ),
                deliver_fn=self._deliver_received_snapshot,
                confirm_fn=self._confirm_received_snapshot,
                reject_fn=self._reject_received_snapshot,
            )
            raw_transport = (
                expert.transport_factory(
                    config, self._handle_message_batch, self._chunk_sink.add
                )
                if expert.transport_factory
                else InProcTransport(
                    config.raft_address,
                    self._handle_message_batch,
                    self._chunk_sink.add,
                )
            )
            # resumable streams: reconnecting senders query this host's
            # receive cursor before re-streaming (docs/BIGSTATE.md);
            # getattr-guarded set so bespoke transport factories without
            # the attribute keep working (they degrade to restart+
            # idempotent re-delivery)
            if hasattr(raw_transport, "resume_handler"):
                raw_transport.resume_handler = self._chunk_sink.resume_cursor
            self.transport = Transport(
                raw_transport,
                self.registry.resolve,
                config.raft_address,
                config.deployment_id,
                unreachable_cb=self._report_unreachable,
                snapshot_source_opener=self._open_snapshot_source,
                snapshot_status_cb=self._report_snapshot_status,
                max_snapshot_send_bytes_per_second=(
                    config.max_snapshot_send_bytes_per_second
                ),
                metrics_registry=self.metrics,
                stream_event_cb=self._stream_event,
            )
            self.transport.start()

            self.metrics.gauge(
                "raft_nodehost_shards", lambda: len(self._nodes)
            )
            self.metrics.gauge(
                "raft_transport_sent_total", lambda: self.transport.metrics["sent"]
            )
            self.metrics.gauge(
                "raft_transport_dropped_total",
                lambda: self.transport.metrics["dropped"],
            )
            self.metrics.gauge(
                "raft_transport_failed_total",
                lambda: self.transport.metrics["failed"],
            )
            self.metrics.gauge(
                "raft_transport_snapshots_sent_total",
                lambda: self.transport.metrics["snapshots_sent"],
            )
            # the snapshot_stream_* surface (docs/BIGSTATE.md): stream
            # egress, resume events, cap-induced sleep and live jobs
            self.metrics.gauge(
                "snapshot_stream_chunks_total",
                lambda: self.transport.metrics["stream_chunks"],
            )
            self.metrics.gauge(
                "snapshot_stream_bytes_total",
                lambda: self.transport.metrics["stream_bytes"],
            )
            self.metrics.gauge(
                "snapshot_stream_resumes_total",
                lambda: self.transport.metrics["stream_resumes"],
            )
            self.metrics.gauge(
                "snapshot_stream_throttle_seconds_total",
                lambda: self.transport.stream_throttled_seconds(),
            )
            self.metrics.gauge(
                "snapshot_stream_active", lambda: self.transport._stream_jobs
            )
            def _proposals_total():
                with self._nodes_lock:
                    return sum(n.proposal_count for n in self._nodes.values())

            self.metrics.gauge(
                "raft_nodehost_proposals_total", _proposals_total
            )
            # engine-health gauges (obs tentpole): scrape-time O(nodes)
            # walks over lock-free per-node counters — the step/apply
            # hot paths pay nothing
            self.metrics.gauge(
                "raft_nodehost_tick_lag_max", self._tick_lag_max
            )
            self.metrics.gauge(
                "raft_nodehost_queue_depth_total", self._queue_depth_total
            )
            self.metrics.gauge(
                "raft_nodehost_apply_lag_max", self._apply_lag_max
            )

            step_engine = (
                expert.step_engine_factory(self) if expert.step_engine_factory else None
            )
            self.engine = ExecEngine(
                self.logdb,
                step_workers=expert.engine.exec_shards,
                apply_workers=expert.engine.apply_shards,
                step_engine=step_engine,
                metrics=self.metrics,
            )
            self.engine.start()

            self._ticks_paused = False
            self._ticker_stop = threading.Event()
            self._ticker = threading.Thread(
                target=self._ticker_main, daemon=True, name="tpu-raft-ticker"
            )
            self._ticker.start()
        except Exception:
            # release everything already started — a same-process retry
            # must not hit DirLockedError, EADDRINUSE or orphan threads
            for closer in ("engine", "transport", "gossip", "logdb"):
                obj = getattr(self, closer, None)
                if obj is not None:
                    try:
                        obj.stop() if closer == "engine" else obj.close()
                    except Exception:  # noqa: BLE001
                        pass
            self._env.close()
            raise

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.events.node_host_shutting_down()
        self._ticker_stop.set()
        self._ticker.join(timeout=2.0)
        with self._nodes_lock:
            nodes = list(self._nodes.values())
            self._nodes.clear()
            self._parked.clear()
        # announce shutdown BEFORE unregistering: step engines must stop
        # letting these replicas participate (win elections, route
        # appends) while the teardown drains — in colocated mode a
        # still-participating row of a closing host strands routed
        # payloads and fail-stops healthy peers
        for n in nodes:
            n.stopping = True
        self.engine.unregister_many([n.shard_id for n in nodes])
        # join worker threads before closing the user SMs: an apply worker
        # may still be inside sm.handle
        self.engine.stop()
        if self.gossip is not None:
            self.gossip.close()
        for n in nodes:
            n.stop()
        self.transport.close()
        self.logdb.close()
        self.events.close()
        # release the dir flock LAST: another process may acquire the dir
        # the moment this unlocks, and the WAL must be closed by then
        self._env.close()

    def _ticker_main(self) -> None:
        import os as _os

        # sweep the per-node loop only every Nth period, crediting N
        # ticks at once (same logical tick rate, 1/N the per-node host
        # cost); see NodeHostConfig.tick_sweep_batch for the timing-
        # granularity caveats.  The env var remains the fallback for
        # deployments that predate the config field.
        batch = self.config.tick_sweep_batch or max(
            1, int(_os.environ.get("TICK_SWEEP_BATCH", "1"))
        )
        period = self.config.rtt_millisecond / 1000.0 * batch
        while not self._ticker_stop.wait(period):
            if self._ticks_paused:
                continue
            self._global_ticks += batch
            with self._nodes_lock:
                nodes = [
                    n for sid, n in self._nodes.items()
                    if sid not in self._parked
                ]
            ready = []
            for n in nodes:
                if n.is_parkable():
                    with self._nodes_lock:
                        # re-check under the lock: a producer may have
                        # raced a wake() between the test and the park,
                        # and stop_shard may have removed the node — a
                        # stale _parked entry would block all ticks to a
                        # later start_replica of the same shard id
                        if (
                            n.is_parkable()
                            and self._nodes.get(n.shard_id) is n
                        ):
                            n.parked_at_tick = self._global_ticks
                            self._parked[n.shard_id] = n
                            rec = self.recorder
                            if rec is not None:
                                rec.record(
                                    n.shard_id, "park",
                                    f"tick={self._global_ticks}",
                                )
                            continue
                for _ in range(batch):
                    n.add_tick()
                ready.append(n.shard_id)
            if ready:
                self.engine.notify_many(ready)

    def _wake_node(self, node) -> None:
        """Producer-side unpark (node.wake): rejoin the active tick set
        and credit the ticks that elapsed while parked."""
        # raftlint: ignore[guarded-by] lock-free fast path; see below
        if node.shard_id not in self._parked:
            # lock-free fast path: wake() rides EVERY producer call
            # (propose, enqueue_received, ...); taking the host-global
            # lock per message would reintroduce the very contention
            # parking removes.  The race is safe: a producer appends to
            # the node's queue BEFORE calling wake, so the ticker's
            # under-lock is_parkable re-check sees the entry and
            # declines to park.
            return
        with self._nodes_lock:
            n = self._parked.pop(node.shard_id, None)
        if n is not None:
            n.grant_ticks(self._global_ticks - n.parked_at_tick)
            rec = self.recorder
            if rec is not None:
                rec.record(
                    n.shard_id, "unpark",
                    f"tick={self._global_ticks} "
                    f"parked_at={n.parked_at_tick}",
                )
            if n.notify_work is not None:
                n.notify_work()

    def pause_ticks(self) -> None:
        """Suspend the logical clock (mass-start tooling).

        Starting tens of thousands of replicas takes wall-clock time
        during which already-started shards would otherwise hit their
        election timeouts and launch full engine step generations,
        starving the start loop of CPU (the r03 10k-shard run spent 13
        minutes in start_replica for this reason).  Pausing ticks while
        loading keeps registration-driven steps (which are cheap) and
        freezes election clocks; ``resume_ticks`` lets every shard's
        randomized timeout start from the same instant.  No reference
        equivalent — Go hosts start replicas in microseconds [U]."""
        self._ticks_paused = True

    def resume_ticks(self) -> None:
        self._ticks_paused = False

    # ------------------------------------------------------------------
    # shard lifecycle
    # ------------------------------------------------------------------
    def start_replica(
        self,
        initial_members: Dict[int, str],
        join: bool,
        sm_factory: Callable,
        config: Config,
    ) -> None:
        """Start this replica of a shard (reference: StartReplica /
        StartConcurrentReplica / StartOnDiskReplica — the SM tier is
        detected from the factory's return type) [U]."""
        if self._closed:
            raise NodeHostClosed("nodehost closed")
        config.validate()
        if not join and not initial_members:
            raise ConfigError("initial members not given for a non-join start")
        with self._nodes_lock:
            if config.shard_id in self._nodes:
                raise ConfigError(f"shard {config.shard_id} already started")
            for pid, addr in initial_members.items():
                self.registry.add(config.shard_id, pid, addr)
            node = Node(
                config=config,
                initial_members=initial_members,
                join=join,
                sm_factory=sm_factory,
                logdb=self.logdb,
                snapshot_storage=self.snapshot_storage,
                transport=self.transport,
                on_leader_updated=self._on_leader_updated,
                event_listener=self.events,
                registry=self.registry,
                tracer=self.tracer,
            )
            self._nodes[config.shard_id] = node
            node.wake = functools.partial(self._wake_node, node)
            self.engine.register(node)
        self.events.node_ready(NodeInfoEvent(config.shard_id, config.replica_id))

    def stop_shard(self, shard_id: int) -> None:
        with self._nodes_lock:
            node = self._nodes.pop(shard_id, None)
            self._parked.pop(shard_id, None)
        if node is None:
            raise ShardNotFound(f"shard {shard_id}")
        self.engine.unregister(shard_id)
        node.stop()

    def stop_replica(self, shard_id: int, replica_id: int) -> None:
        self.stop_shard(shard_id)

    # ------------------------------------------------------------------
    # message plumbing
    # ------------------------------------------------------------------
    def _handle_message_batch(self, batch: MessageBatch) -> None:
        if self._closed:
            return
        if (
            self.config.deployment_id
            and batch.deployment_id
            and batch.deployment_id != self.config.deployment_id
        ):
            _log.warning("dropping batch with wrong deployment id")
            return
        touched = set()
        with self._nodes_lock:
            for m in batch.messages:
                node = self._nodes.get(m.shard_id)
                if node is None or node.replica_id != m.to:
                    continue
                # learn the sender's return address from the batch (the
                # reference's MessageBatch.SourceAddress): a replica that
                # joined with empty members can respond BEFORE the
                # membership config change commits — without this the
                # first contact deadlocks (it cannot ack, so the leader
                # never resends)
                if batch.source_address and m.from_:
                    self.registry.learn(
                        m.shard_id, m.from_, batch.source_address
                    )
                node.enqueue_received(m)
                touched.add(m.shard_id)
        if touched:
            self.engine.notify_many(touched)

    # -- snapshot streaming plumbing -----------------------------------
    def _open_snapshot_source(self, ss):
        from .storage.snapshotter import SnapshotSource

        return SnapshotSource(self.snapshot_storage, ss)

    def _stream_event(self, shard_id: int, kind: str, detail: str) -> None:
        """Stream-job lifecycle (start/resume/complete/fail) lands in
        the shard's flight-recorder lane: the post-incident timeline of
        a laggard catch-up shows exactly when the streamer died and from
        which chunk it resumed (docs/BIGSTATE.md)."""
        rec = self.recorder
        if rec is not None:
            rec.record(shard_id, kind, detail)

    def set_snapshot_send_rate(self, bytes_per_second: int) -> None:
        """Retune the host-wide snapshot-stream bandwidth cap at
        runtime (0 removes it).  The cap is one token bucket shared by
        every stream job of this host; the ``bigstate.pacing.
        CapFeedback`` loop drives this knob to keep follower catch-up
        from starving the commit path.  A host fronted by a
        ``gateway.Gateway`` gets that loop wired to a LIVE latency
        source automatically — the gateway feeds its LatencyBudget's
        commit latencies into a per-host AIMD loop unless
        ``GatewayConfig(cap_feedback=False)`` opts out
        (docs/GATEWAY.md "Snapshot-cap feedback")."""
        self.transport.set_snapshot_send_rate(bytes_per_second)

    def _deliver_received_snapshot(self, m: Message) -> None:
        """A fully-reassembled snapshot enters the raft path like any other
        received message."""
        self._handle_message_batch(MessageBatch(messages=(m,)))

    def _confirm_received_snapshot(
        self, shard_id: int, from_replica: int, to_replica: int
    ) -> None:
        """Tell the sender its stream arrived (reference: the receiving
        side's SnapshotReceived message [U])."""
        self.transport.send(
            Message(
                type=MessageType.SNAPSHOT_RECEIVED,
                shard_id=shard_id,
                from_=to_replica,
                to=from_replica,
            )
        )

    def _reject_received_snapshot(
        self, shard_id: int, from_replica: int, to_replica: int
    ) -> None:
        """A completed stream failed container validation: tell the
        SENDER over the wire so its raft peer clears the pending
        snapshot and retries (without this the remote would stay in
        SNAPSHOT wait forever on transports where the sender cannot
        observe the final-chunk rejection)."""
        self.transport.send(
            Message(
                type=MessageType.SNAPSHOT_STATUS,
                shard_id=shard_id,
                from_=to_replica,
                to=from_replica,
                reject=True,
            )
        )

    def _report_snapshot_status(
        self, shard_id: int, to_replica: int, failed: bool
    ) -> None:
        """A stream job finished/failed: tell the local sending peer
        (reference: ReportSnapshotStatus [U])."""
        with self._nodes_lock:
            node = self._nodes.get(shard_id)
        if node is None:
            return
        node.enqueue_received(
            Message(
                type=MessageType.SNAPSHOT_STATUS,
                shard_id=shard_id,
                from_=to_replica,
                to=node.replica_id,
                reject=failed,
            )
        )
        self.engine.notify(shard_id)

    def _report_unreachable(self, m) -> None:
        with self._nodes_lock:
            node = self._nodes.get(m.shard_id)
        if node is None:
            return
        node.enqueue_received(Message(type=MessageType.UNREACHABLE, from_=m.to))
        self.engine.notify(m.shard_id)

    def _on_leader_updated(
        self, shard_id: int, replica_id: int, term: int, leader_id: int
    ) -> None:
        rec = self.recorder
        if rec is not None:
            rec.record(
                shard_id, "leader_change",
                f"replica={replica_id} term={term} leader={leader_id}",
            )
        self.events.leader_updated(
            LeaderInfo(
                shard_id=shard_id,
                replica_id=replica_id,
                term=term,
                leader_id=leader_id,
            )
        )

    # ------------------------------------------------------------------
    # request APIs
    # ------------------------------------------------------------------
    def _get_node(self, shard_id: int) -> Node:
        if self._closed:
            raise NodeHostClosed("nodehost closed")
        with self._nodes_lock:
            node = self._nodes.get(shard_id)
        if node is None:
            raise ShardNotFound(f"shard {shard_id} not found")
        return node

    def _timeout_ticks(self, timeout: float) -> int:
        return max(1, int(timeout * 1000 / self.config.rtt_millisecond))

    def get_noop_session(self, shard_id: int) -> Session:
        return Session.noop(shard_id)

    # -- proposals --------------------------------------------------------
    def propose(
        self, session: Session, cmd: bytes, timeout: float, parent=None
    ) -> RequestState:
        node = self._get_node(session.shard_id)
        tracer = self.tracer  # None when disabled: one attribute load
        span = None
        if tracer is not None and parent is not UNSAMPLED:
            if parent is not None:
                # continue a caller-held trace (e.g. the client retry
                # loop's root span) — already sampled at its root
                span = tracer.start_span(
                    "propose", parent.trace_id, parent.span_id,
                    shard_id=session.shard_id,
                )
            else:
                span = tracer.start_trace("propose", shard_id=session.shard_id)
            if span is not None:
                span.annotate(f"client:propose bytes={len(cmd)}")
        try:
            rs = node.propose(
                session, cmd, self._timeout_ticks(timeout), span=span
            )
        except Exception as e:
            # a rejected request (SystemBusy, closed shard, ...) must
            # still reach the finished-span ring — the weakly-held open
            # span would otherwise be GC'd unended and the very
            # requests an operator debugs would vanish from dumps
            if span is not None:
                span.end(status=type(e).__name__)
            raise
        self.engine.notify(session.shard_id)
        return rs

    def sync_propose(
        self, session: Session, cmd: bytes, timeout: float = 5.0, parent=None
    ) -> Result:
        rs = self.propose(session, cmd, timeout, parent=parent)
        return _check(rs.wait(timeout), rs)

    # -- sessions ---------------------------------------------------------
    def sync_get_session(self, shard_id: int, timeout: float = 5.0) -> Session:
        s = Session.new_session(shard_id)
        node = self._get_node(shard_id)
        rs = node.propose_session_op(s, self._timeout_ticks(timeout))
        self.engine.notify(shard_id)
        _check(rs.wait(timeout), rs)
        s.prepare_for_propose()
        return s

    def sync_close_session(self, session: Session, timeout: float = 5.0) -> None:
        session.prepare_for_unregister()
        node = self._get_node(session.shard_id)
        rs = node.propose_session_op(session, self._timeout_ticks(timeout))
        self.engine.notify(session.shard_id)
        _check(rs.wait(timeout), rs)

    # -- reads ------------------------------------------------------------
    def read_index(self, shard_id: int, timeout: float) -> RequestState:
        node = self._get_node(shard_id)
        tracer = self.tracer
        span = None
        if tracer is not None:
            span = tracer.start_trace("read_index", shard_id=shard_id)
        try:
            rs = node.read_index(self._timeout_ticks(timeout), span=span)
        except Exception as e:
            if span is not None:
                span.end(status=type(e).__name__)
            raise
        self.engine.notify(shard_id)
        return rs

    def sync_read(self, shard_id: int, query, timeout: float = 5.0):
        rs = self.read_index(shard_id, timeout)
        _check(rs.wait(timeout), rs)
        self._count_read("read_index")
        return self._get_node(shard_id).lookup(query)

    def stale_read(self, shard_id: int, query):
        return self._get_node(shard_id).stale_read(query)

    def _count_read(self, path: str) -> None:
        self._read_paths[path] = self._read_paths.get(path, 0) + 1
        c = self._read_counters.get(path)
        if c is not None:
            c.add()

    def read_path_counts(self) -> Dict[str, int]:
        """Cumulative reads served per readplane path on this host
        (lease / read_index / follower / bounded / bounded_shed) —
        surfaced through RPC STATS and the readplane smoke."""
        return dict(self._read_paths)

    def follower_read(self, shard_id: int, query, timeout: float = 5.0):
        """FOLLOWER_LINEARIZABLE: run the ReadIndex confirmation round
        through the leader (the raft layer forwards when this replica
        is a follower), wait until the local RSM has applied past the
        confirmed index, then serve from the LOCAL state machine.
        Returns ``(value, applied_index)``.  Linearizable — safety
        argument in docs/READPLANE.md; a leadership change mid-round
        fails the future fast (Raft.drop_pending_read_indexes) so the
        caller re-confirms instead of trusting a deposed leader."""
        rs = self.read_index(shard_id, timeout)
        _check(rs.wait(timeout), rs)
        node = self._get_node(shard_id)
        value = node.lookup(query)
        self._count_read("follower")
        return value, node.sm.last_applied

    def bounded_read(
        self, shard_id: int, query, bound_ticks: int = BOUND_TICKS_DEFAULT
    ) -> ReadResult:
        """BOUNDED_STALENESS: serve immediately from the local state
        machine, stamped with the applied index and staleness in ticks;
        raise :class:`StaleBoundExceeded` when the replica cannot prove
        the stamp stays within ``bound_ticks`` (Node.bounded_read_probe
        has the gate)."""
        node = self._get_node(shard_id)
        ok, applied, staleness = node.bounded_read_probe(bound_ticks)
        if not ok:
            self._count_read("bounded_shed")
            raise StaleBoundExceeded(
                f"shard {shard_id}: staleness {staleness} ticks exceeds "
                f"bound {bound_ticks}"
            )
        value = node.lookup(query)
        self._count_read("bounded")
        return ReadResult(
            value=value, path="bounded",
            applied_index=applied, staleness_ticks=staleness,
        )

    def read_at_replica(
        self,
        shard_id: int,
        query,
        consistency: Consistency = Consistency.LINEARIZABLE,
        timeout: float = 5.0,
        bound_ticks: int = BOUND_TICKS_DEFAULT,
        lease_margin_ticks: int = 2,
    ) -> ReadResult:
        """One explicit-consistency read against THIS host's replica
        (docs/READPLANE.md; the cross-replica routing lives in the
        gateway).  LINEARIZABLE tries the lease fast path and falls
        back to the ReadIndex quorum round; the other levels map to
        :meth:`follower_read` / :meth:`bounded_read`."""
        if consistency == Consistency.FOLLOWER_LINEARIZABLE:
            value, applied = self.follower_read(shard_id, query, timeout)
            return ReadResult(
                value=value, path="follower", applied_index=applied
            )
        if consistency == Consistency.BOUNDED_STALENESS:
            return self.bounded_read(shard_id, query, bound_ticks)
        ok, value = self.try_lease_read(shard_id, query, lease_margin_ticks)
        if ok:
            return ReadResult(value=value, path="lease")
        value = self.sync_read(shard_id, query, timeout)
        return ReadResult(value=value, path="read_index")

    def try_lease_read(
        self, shard_id: int, query, margin_ticks: int = 2
    ) -> tuple:
        """Serve a linearizable read from the local replica WITHOUT the
        per-read ReadIndex quorum round trip, iff this replica holds a
        CheckQuorum leader lease with more than ``margin_ticks`` to
        spare (gateway/ fast-read path; safety argument in
        ``Node.lease_remaining_ticks`` and docs/GATEWAY.md).  Returns
        ``(True, value)`` on a lease-served read, ``(False, None)``
        when the caller must fall back to :meth:`read_index`/
        :meth:`sync_read`.  The margin absorbs tick drift between
        hosts and the probe-to-lookup race; requires the shard's
        ``Config.check_quorum`` or the lease is never held."""
        node = self._get_node(shard_id)
        if not node.lease_held(margin_ticks):
            return False, None
        self._count_read("lease")
        return True, node.lookup(query)

    def lease_status(self, shard_id: int) -> dict:
        """Lease observability probe (tests, metrics scrapes)."""
        node = self._get_node(shard_id)
        return {
            "is_leader": node.peer.is_leader(),
            "check_quorum": node.peer.raft.check_quorum,
            "remaining_ticks": node.lease_remaining_ticks(),
        }

    # -- membership -------------------------------------------------------
    def _sync_config_change(
        self,
        shard_id: int,
        cc: ConfigChange,
        timeout: float,
    ) -> None:
        node = self._get_node(shard_id)
        rs = node.request_config_change(cc, self._timeout_ticks(timeout))
        self.engine.notify(shard_id)
        _check(rs.wait(timeout), rs)
        # registry sync happens in Node._complete_applied on every replica
        # when the config-change entry applies; nothing extra to do here

    def sync_request_add_replica(
        self,
        shard_id: int,
        replica_id: int,
        target: str,
        config_change_index: int = 0,
        timeout: float = 5.0,
    ) -> None:
        self._sync_config_change(
            shard_id,
            ConfigChange(
                config_change_id=config_change_index,
                type=ConfigChangeType.ADD_REPLICA,
                replica_id=replica_id,
                address=target,
            ),
            timeout,
        )

    def sync_request_add_non_voting(
        self, shard_id, replica_id, target, config_change_index=0, timeout=5.0
    ) -> None:
        self._sync_config_change(
            shard_id,
            ConfigChange(
                config_change_id=config_change_index,
                type=ConfigChangeType.ADD_NON_VOTING,
                replica_id=replica_id,
                address=target,
            ),
            timeout,
        )

    def sync_request_add_witness(
        self, shard_id, replica_id, target, config_change_index=0, timeout=5.0
    ) -> None:
        self._sync_config_change(
            shard_id,
            ConfigChange(
                config_change_id=config_change_index,
                type=ConfigChangeType.ADD_WITNESS,
                replica_id=replica_id,
                address=target,
            ),
            timeout,
        )

    def sync_request_delete_replica(
        self, shard_id, replica_id, config_change_index=0, timeout=5.0
    ) -> None:
        self._sync_config_change(
            shard_id,
            ConfigChange(
                config_change_id=config_change_index,
                type=ConfigChangeType.REMOVE_REPLICA,
                replica_id=replica_id,
            ),
            timeout,
        )

    def sync_get_shard_membership(self, shard_id: int, timeout: float = 5.0) -> Membership:
        rs = self.read_index(shard_id, timeout)
        _check(rs.wait(timeout), rs)
        return self._get_node(shard_id).get_membership()

    def get_shard_membership(self, shard_id: int) -> Membership:
        return self._get_node(shard_id).get_membership()

    # -- snapshots --------------------------------------------------------
    def sync_request_snapshot(
        self, shard_id: int, compaction_overhead: int = 0, timeout: float = 5.0
    ) -> int:
        node = self._get_node(shard_id)
        rs = node.request_snapshot(
            compaction_overhead or node.config.compaction_overhead,
            self._timeout_ticks(timeout),
        )
        self.engine.notify(shard_id)
        return _check(rs.wait(timeout), rs).value

    # -- disaster recovery (bigstate/dr.py; docs/BIGSTATE.md) -----------
    def export_snapshot(
        self, shard_id: int, export_dir: str, timeout: float = 10.0
    ):
        """DR export: snapshot the shard's current applied state and
        write a self-describing portable archive to ``export_dir``
        (container + external files + ``MANIFEST.json`` with
        shard/replica/index/term/membership and per-chunk checksums).
        Streamed end to end — a GB-scale state machine never
        materializes in memory.  Returns the ``pb.SnapshotManifest``.
        """
        from .bigstate.dr import write_archive

        node = self._get_node(shard_id)
        try:
            self.sync_request_snapshot(shard_id, timeout=timeout)
        except RequestRejected:
            pass  # applied index unchanged since the last snapshot: use it
        ss = self.logdb.get_snapshot(shard_id, node.replica_id)
        if ss.is_empty():
            raise RequestError(
                f"shard {shard_id} has no snapshot to export (no applied "
                "entries yet?)"
            )
        return write_archive(self.snapshot_storage, ss, export_dir)

    def import_snapshot(
        self,
        export_dir: str,
        shard_id: int,
        replica_id: int,
        members: Dict[int, str],
    ):
        """DR import: seed this host with an exported archive under a
        REWRITTEN membership, before ``start_replica`` for the shard.
        Every member listed must import the same archive with the same
        membership on its own host (reference: tools.ImportSnapshot
        preconditions [U]).  Verifies the manifest's per-chunk checksums
        and the container's own block CRCs before touching the logdb.
        Returns the seeded ``pb.Snapshot``."""
        from .bigstate.dr import import_archive

        return import_archive(self, export_dir, shard_id, replica_id, members)

    # -- leadership -------------------------------------------------------
    def request_leader_transfer(self, shard_id: int, target_id: int) -> None:
        node = self._get_node(shard_id)
        node.request_leader_transfer(target_id, self._timeout_ticks(5.0))
        self.engine.notify(shard_id)

    def get_leader_id(self, shard_id: int):
        node = self._get_node(shard_id)
        lid = node.peer.leader_id()
        return lid, lid != 0

    def is_leader_of(self, shard_id: int) -> bool:
        """True iff this host's replica of ``shard_id`` currently leads
        it (routing-cache discovery probe; False for absent shards —
        discovery sweeps hosts that may not carry the shard at all)."""
        with self._nodes_lock:
            node = self._nodes.get(shard_id)
        if node is None or node.stopped or node.stopping:
            return False
        lid = node.leader_id
        return bool(lid) and lid == node.replica_id

    # -- info -------------------------------------------------------------
    def pending_request_counts(self, shard_id: int) -> Dict[str, int]:
        """Outstanding request futures per table for one LIVE shard
        (the audit harness' leak probe; raises ShardNotFound once the
        shard is stopped — to assert a stopped node's tables drained to
        zero, hold the Node reference across ``stop_shard`` and len()
        its tables directly, as tests/test_scale.py's churn phase
        does)."""
        node = self._get_node(shard_id)
        return {
            "proposal": len(node.pending_proposal),
            "read_index": len(node.pending_read_index),
            "config_change": len(node.pending_config_change),
            "snapshot": len(node.pending_snapshot),
            "leader_transfer": len(node.pending_leader_transfer),
        }

    def write_health_metrics(self, writer) -> None:
        """Prometheus-text metric export (reference:
        NodeHost.WriteHealthMetrics [U]); enable via
        NodeHostConfig.enable_metrics."""
        writer.write(self.metrics.export_text())

    # -- event taps (gateway/ routing-cache invalidation) --------------
    def add_event_tap(self, fn) -> None:
        """Attach a synchronous ``fn(name, args)`` tap to this host's
        event fanout; sees every system event plus ``leader_updated``
        (events.EventFanout.add_tap)."""
        self.events.add_tap(fn)

    def remove_event_tap(self, fn) -> None:
        self.events.remove_tap(fn)

    # -- observability (obs/, docs/OBSERVABILITY.md) -------------------
    def _recorder_tap(self, name: str, args) -> None:
        """EventFanout tap: every system event also lands in the flight
        recorder, synchronously (the fanout queue can drop under
        pressure; the recorder must not miss state transitions)."""
        rec = self.recorder
        if rec is None:
            return
        info = args[0] if args else None
        shard = getattr(info, "shard_id", 0) or 0
        rec.record(shard, f"event:{name}", repr(info) if info is not None else "")

    def _tick_lag_max(self) -> int:
        with self._nodes_lock:
            nodes = list(self._nodes.values())
        return max((n.tick_lag() for n in nodes), default=0)

    def _queue_depth_total(self) -> int:
        with self._nodes_lock:
            nodes = list(self._nodes.values())
        return sum(n.queued_inputs() for n in nodes)

    def _apply_lag_max(self) -> int:
        with self._nodes_lock:
            nodes = list(self._nodes.values())
        lag = 0
        for n in nodes:
            try:
                lag = max(lag, n.peer.committed() - n.sm.last_applied)
            except Exception:  # noqa: BLE001 — node mid-stop
                continue
        return lag

    def dump_timeline(self, shard_id=None, writer=None) -> str:
        """Merged human-readable timeline for this host: flight-recorder
        state transitions interleaved with trace spans/annotations.
        This is the "where did these 4 seconds go?" view; cross-host
        merges use :func:`dragonboat_tpu.obs.merged_timeline` over the
        hosts' recorders/tracers."""
        from .obs import format_timeline, merged_timeline

        out = format_timeline(
            merged_timeline(
                recorders=(self.recorder,),
                tracers=(self.tracer,),
                shard_id=shard_id,
            )
        )
        if writer is not None:
            writer.write(out)
        return out

    def export_trace_json(self, path: Optional[str] = None) -> str:
        """Chrome/Perfetto ``trace_event`` JSON of this host's recorded
        spans (open in ui.perfetto.dev).  Empty trace when tracing is
        disabled."""
        data = (
            self.tracer.export_json()
            if self.tracer is not None
            else '{"traceEvents": []}'
        )
        if path:
            with open(path, "w") as f:
                f.write(data)
        return data

    def get_nodehost_info(self) -> dict:
        with self._nodes_lock:
            return {
                "raft_address": self.config.raft_address,
                "shards": [
                    {
                        "shard_id": n.shard_id,
                        "replica_id": n.replica_id,
                        "leader_id": n.leader_id,
                        "term": n.peer.term(),
                        "committed": n.peer.committed(),
                        "applied": n.sm.last_applied,
                    }
                    for n in self._nodes.values()
                ],
            }

    def balance_shard_stats(self) -> list:
        """Per-replica stats for the balance control plane's collector
        (balance/view.py): leader identity, applied index, cumulative
        proposal count and the replica's view of the shard membership.
        Cheap reads off producer threads — same benign races as
        :meth:`get_nodehost_info`."""
        with self._nodes_lock:
            nodes = list(self._nodes.values())
        out = []
        for n in nodes:
            if n.stopped or n.stopping:
                continue
            dev = self.engine.device_coordinate(n.shard_id)
            out.append(
                {
                    "shard_id": n.shard_id,
                    "replica_id": n.replica_id,
                    "leader_id": n.leader_id,
                    "term": n.peer.term(),
                    "applied": n.sm.last_applied,
                    "proposals": n.proposal_count,
                    "membership": n.get_membership(),
                    # chip coordinate of the engine row (None: host
                    # path / no mesh) — the balance plane's new
                    # placement dimension (docs/MULTICHIP.md)
                    "device": -1 if dev is None else dev,
                }
            )
        return out

    def device_chip_count(self) -> int:
        """Chips this host's step engine spreads rows over (collector
        input for the per-chip-capacity balance dimension)."""
        return self.engine.device_chip_count()

    def raft_address(self) -> str:
        return self.config.raft_address

    @property
    def uptime_s(self) -> float:
        """Seconds since this NodeHost was constructed (obs identity)."""
        return time.monotonic() - self._started_mono
