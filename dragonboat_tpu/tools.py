"""Disaster-recovery tools: snapshot export and import.

reference: tools/import.go (ImportSnapshot) and the exported-snapshot
flow of SyncRequestSnapshot [U].  The scenario: a shard has lost its
quorum permanently.  An exported snapshot from a surviving replica is
imported on fresh hosts with a REWRITTEN membership, and the shard
restarts from the snapshot with the new member set.

Export dir layout:
    <dir>/snapshot.bin   checksummed payload (FileSnapshotStorage format)
    <dir>/META           wire-encoded Snapshot metadata
"""
from __future__ import annotations

import os
import shutil
from typing import Dict

from .pb import Membership, Snapshot
from .transport.wire import decode_snapshot_meta, encode_snapshot_meta

META_FILENAME = "META"
PAYLOAD_FILENAME = "snapshot.bin"


def export_snapshot(nodehost, shard_id: int, export_dir: str) -> Snapshot:
    """Write the shard's most recent snapshot to ``export_dir``.

    Call ``nodehost.sync_request_snapshot(shard_id)`` first if the shard
    has never snapshotted.
    """
    import io as _io

    from .storage.snapshotio import SnapshotReader

    replica_id = nodehost._get_node(shard_id).replica_id
    ss = nodehost.logdb.get_snapshot(shard_id, replica_id)
    if ss.is_empty():
        raise ValueError(f"shard {shard_id} has no snapshot to export")
    os.makedirs(export_dir, exist_ok=True)
    storage = nodehost.snapshot_storage
    # lease: snapshot GC must not delete the dir mid-copy; external files
    # (ISnapshotFileCollection) are part of the snapshot and must travel
    with storage.lease(ss.filepath):
        payload = storage.load(ss.filepath)
        with open(os.path.join(export_dir, PAYLOAD_FILENAME), "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        for sf in SnapshotReader(_io.BytesIO(payload)).external_files:
            src = storage.external_path(ss.filepath, sf.filepath)
            shutil.copyfile(src, os.path.join(export_dir, sf.filepath))
    with open(os.path.join(export_dir, META_FILENAME), "wb") as f:
        f.write(encode_snapshot_meta(ss))
        f.flush()
        os.fsync(f.fileno())
    return ss


def import_snapshot(
    nodehost,
    export_dir: str,
    shard_id: int,
    replica_id: int,
    members: Dict[int, str],
) -> Snapshot:
    """Seed ``nodehost`` with an exported snapshot under a rewritten
    membership, BEFORE start_replica for the shard.

    ``members`` is the complete new voter set (replica_id -> address)
    and MUST include ``replica_id`` itself; every listed replica must
    import the same snapshot with the same membership (reference:
    tools.ImportSnapshot preconditions [U]).
    """
    if replica_id not in members:
        raise ValueError(f"replica {replica_id} not in new membership")
    with open(os.path.join(export_dir, META_FILENAME), "rb") as f:
        meta = decode_snapshot_meta(f.read())
    if meta.shard_id != shard_id:
        raise ValueError(
            f"export is for shard {meta.shard_id}, not {shard_id}"
        )
    with open(os.path.join(export_dir, PAYLOAD_FILENAME), "rb") as f:
        raw = f.read()
    payload = raw
    # the v2 container self-validates per section; walk every block so
    # a corrupt export fails HERE, not at replica recovery
    import io as _io

    from .storage.snapshotio import SnapshotCorruptError, SnapshotReader

    try:
        reader = SnapshotReader(_io.BytesIO(payload))
        reader.validate()
    except SnapshotCorruptError as e:
        raise IOError(f"corrupt snapshot export in {export_dir}: {e}")
    # external files must be present in the export — importing without
    # them would fail-stop the replica at recovery
    for sf in reader.external_files:
        if not os.path.exists(os.path.join(export_dir, sf.filepath)):
            raise IOError(
                f"export in {export_dir} is missing external file "
                f"{sf.filepath}"
            )
    path = nodehost.snapshot_storage.save(
        shard_id, replica_id, meta.index, payload, suffix="imported"
    )
    for sf in reader.external_files:
        shutil.copyfile(
            os.path.join(export_dir, sf.filepath),
            nodehost.snapshot_storage.external_path(path, sf.filepath),
        )
    new_membership = Membership(
        config_change_id=meta.membership.config_change_id + 1,
        addresses=dict(members),
    )
    ss = Snapshot(
        filepath=path,
        file_size=len(payload),
        index=meta.index,
        term=meta.term,
        membership=new_membership,
        shard_id=shard_id,
        replica_id=replica_id,
        imported=True,
        compression=meta.compression,
    )
    nodehost.logdb.import_snapshot(ss, replica_id)
    return ss
