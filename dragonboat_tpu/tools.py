"""Disaster-recovery tools: snapshot export and import.

reference: tools/import.go (ImportSnapshot) and the exported-snapshot
flow of SyncRequestSnapshot [U].  The scenario: a shard has lost its
quorum permanently.  An exported snapshot from a surviving replica is
imported on fresh hosts with a REWRITTEN membership, and the shard
restarts from the snapshot with the new member set.

These are thin compatibility wrappers over :mod:`.bigstate.dr`, which
owns the archive format (MANIFEST.json with per-chunk checksums + the
legacy META, everything streamed with bounded memory — the old
whole-blob ``storage.load``/``f.read()`` path could not export a state
machine larger than RAM).  New code should prefer the NodeHost methods
``export_snapshot``/``import_snapshot``.

Export dir layout: see bigstate/dr.py (MANIFEST.json, META,
snapshot.bin, external-* siblings).
"""
from __future__ import annotations

from typing import Dict

from .bigstate.dr import (  # noqa: F401 — re-exported for callers
    MANIFEST_FILENAME,
    META_FILENAME,
    PAYLOAD_FILENAME,
    ArchiveError,
    import_archive,
    write_archive,
)
from .pb import Snapshot


def export_snapshot(nodehost, shard_id: int, export_dir: str) -> Snapshot:
    """Write the shard's most recent snapshot to ``export_dir``.

    Call ``nodehost.sync_request_snapshot(shard_id)`` first if the shard
    has never snapshotted (or use ``NodeHost.export_snapshot``, which
    snapshots the CURRENT applied state for you).
    """
    replica_id = nodehost._get_node(shard_id).replica_id
    ss = nodehost.logdb.get_snapshot(shard_id, replica_id)
    if ss.is_empty():
        raise ValueError(f"shard {shard_id} has no snapshot to export")
    write_archive(nodehost.snapshot_storage, ss, export_dir)
    return ss


def import_snapshot(
    nodehost,
    export_dir: str,
    shard_id: int,
    replica_id: int,
    members: Dict[int, str],
) -> Snapshot:
    """Seed ``nodehost`` with an exported snapshot under a rewritten
    membership, BEFORE start_replica for the shard.

    ``members`` is the complete new voter set (replica_id -> address)
    and MUST include ``replica_id`` itself; every listed replica must
    import the same snapshot with the same membership (reference:
    tools.ImportSnapshot preconditions [U]).
    """
    return import_archive(nodehost, export_dir, shard_id, replica_id, members)
