"""Leader's per-follower replication flow state.

reference: internal/raft/remote.go [U].  States:

  * RETRY      — probing: send one batch, then pause (WAIT) until a
                 response or heartbeat-resp resumes it.
  * WAIT       — paused probe.
  * REPLICATE  — pipelining: optimistic ``next`` advance on send.
  * SNAPSHOT   — streaming a snapshot; paused until SnapshotStatus.

The integer values are part of the device SoA encoding (ops/state.py).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List


class RemoteState(enum.IntEnum):
    RETRY = 0
    WAIT = 1
    REPLICATE = 2
    SNAPSHOT = 3


@dataclass(slots=True)
class Remote:
    match: int = 0
    next: int = 1
    state: RemoteState = RemoteState.RETRY
    snapshot_index: int = 0
    active: bool = False  # contacted since last CheckQuorum sweep
    # CheckQuorum-lease contact evidence (raft.lease_remaining_ticks):
    # ``last_resp_tick`` is the leader tick a response PROVED contact at
    # — anchored at a probe's SEND tick, never at response receipt (a
    # response can sit in flight or queue in the leader's inbox
    # arbitrarily long; anchoring at receipt would extend the claimed
    # lease past the follower's actual vote-refusal window by that
    # delay — review finding).  ``probe_queue`` is a FIFO of
    # outstanding probe send ticks: each response pops the head.  Both
    # transports deliver per peer pair in order and the follower
    # responds in processing order, so the popped tick is the send tick
    # of the answered probe — or OLDER whenever any earlier probe or
    # response was dropped (the unanswered entry stays queued and
    # shifts every later pop one probe older), which only ever makes
    # the anchor conservative.  The queue is never cleared mid-
    # leadership (clearing let a delayed response anchor at a probe
    # armed AFTER it — review finding); accumulated message loss thus
    # decays lease availability (reads fall back to ReadIndex), never
    # safety, and the queue resets with fresh leadership.  Bounded:
    # arms are skipped when full (skipping keeps pops older = safe).
    last_resp_tick: int = -1
    probe_queue: List[int] = field(default_factory=list)

    def reset(self, next_index: int, match: int = 0) -> None:
        self.match = match
        self.next = next_index
        self.state = RemoteState.RETRY
        self.snapshot_index = 0

    def is_paused(self) -> bool:
        return self.state in (RemoteState.WAIT, RemoteState.SNAPSHOT)

    def is_active(self) -> bool:
        return self.active

    def set_active(self) -> None:
        self.active = True

    def clear_active(self) -> None:
        self.active = False

    # -- state transitions ------------------------------------------------
    def become_retry(self) -> None:
        if self.state == RemoteState.SNAPSHOT:
            self.next = max(self.match + 1, self.snapshot_index + 1)
        else:
            self.next = self.match + 1
        self.snapshot_index = 0
        self.state = RemoteState.RETRY

    def become_wait(self) -> None:
        self.become_retry()
        self.state = RemoteState.WAIT

    def become_replicate(self) -> None:
        self.next = self.match + 1
        self.snapshot_index = 0
        self.state = RemoteState.REPLICATE

    def become_snapshot(self, index: int) -> None:
        self.state = RemoteState.SNAPSHOT
        self.snapshot_index = index

    def clear_pending_snapshot(self) -> None:
        self.snapshot_index = 0

    def retry_to_wait(self) -> None:
        if self.state == RemoteState.RETRY:
            self.state = RemoteState.WAIT

    def wait_to_retry(self) -> None:
        if self.state == RemoteState.WAIT:
            self.state = RemoteState.RETRY

    # -- progress ---------------------------------------------------------
    def progress(self, last_index: int) -> None:
        """Record that entries up to ``last_index`` were just sent."""
        if self.state == RemoteState.REPLICATE:
            self.next = last_index + 1
        elif self.state == RemoteState.RETRY:
            self.retry_to_wait()
        else:
            raise RuntimeError(f"progress called in state {self.state}")

    def respond_to(self) -> None:
        """A response arrived: unpause probing."""
        if self.state == RemoteState.WAIT:
            self.state = RemoteState.RETRY

    def try_update(self, index: int) -> bool:
        """Follower acked ``index``; returns True if match advanced."""
        if self.next < index + 1:
            self.next = index + 1
        if self.match < index:
            self.match = index
            if self.state == RemoteState.WAIT:
                self.state = RemoteState.RETRY
            return True
        return False

    def decrease(self, rejected_index: int, peer_last_index: int) -> bool:
        """Handle a log-matching rejection (reference: remote.decreaseTo [U]).

        ``rejected_index`` is the prev_log_index the follower rejected;
        ``peer_last_index`` the follower's hint (its last index).
        Returns False if the rejection is stale.
        """
        if self.state == RemoteState.REPLICATE:
            if rejected_index <= self.match:
                return False  # stale
            self.become_retry()
            return True
        if self.next - 1 != rejected_index:
            return False  # stale
        self.next = max(min(rejected_index, peer_last_index + 1), self.match + 1, 1)
        self.wait_to_retry()
        return True
