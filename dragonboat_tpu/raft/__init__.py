"""Pure raft protocol core (reference: internal/raft/ [U]).

No I/O anywhere in this package: the state machine is a pure function of
(state, message) -> (state', outputs), driven via ``Peer`` and observed via
``pb.Update``.  This is the semantic spec that the vectorized TPU step
kernel (``dragonboat_tpu.ops``) must reproduce bit-exactly on its hot path.
"""
from .raft import Raft, RaftRole
from .peer import Peer, PeerInfo
from .log import EntryLog, InMemory, InMemLogReader, LogCompactedError, LogUnavailableError

__all__ = [
    "Raft",
    "RaftRole",
    "Peer",
    "PeerInfo",
    "EntryLog",
    "InMemory",
    "InMemLogReader",
    "LogCompactedError",
    "LogUnavailableError",
]
