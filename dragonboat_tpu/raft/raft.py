"""The raft protocol state machine — pure, deterministic, no I/O.

reference: internal/raft/raft.go [U] (which itself descends from etcd-raft;
the etcd-style protocol test suite in tests/test_raft_*.py is the parity
oracle for the vectorized TPU kernel in dragonboat_tpu/ops).

Determinism: election-timeout randomization uses a counter-based splitmix64
hash of (shard_id, replica_id, term, reset_seq) — no global RNG — so a
trace replayed against the device kernel produces bit-identical behavior
(SURVEY.md §7 "Bit-exact parity").
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional, Tuple

from .. import settings
from ..logger import get_logger
from ..pb import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    NO_LEADER,
    NO_NODE,
    ReadyToRead,
    Snapshot,
    State,
    SystemCtx,
)
from .log import EntryLog, ILogReader, LogCompactedError, LogUnavailableError
from .read_index import ReadIndex
from .remote import Remote, RemoteState

_log = get_logger("raft")


class RaftRole(enum.IntEnum):
    """Role encoding — values are part of the device SoA layout."""

    FOLLOWER = 0
    PRE_CANDIDATE = 1
    CANDIDATE = 2
    LEADER = 3
    NON_VOTING = 4
    WITNESS = 5


def splitmix32(x: int) -> int:
    """Counter-based deterministic 32-bit hash (murmur3 finalizer over a
    Weyl-incremented counter); identical formula on device
    (ops/kernel.py) — this is what makes election jitter replayable.
    32-bit on purpose: TPUs have no native int64 and the device kernel
    runs entirely in int32/uint32 lanes."""
    x = (x + 0x9E3779B9) & 0xFFFFFFFF
    z = x
    z ^= z >> 16
    z = (z * 0x85EBCA6B) & 0xFFFFFFFF
    z ^= z >> 13
    z = (z * 0xC2B2AE35) & 0xFFFFFFFF
    z ^= z >> 16
    return z


def election_jitter(shard_id: int, replica_id: int, seq: int, span: int) -> int:
    """Deterministic jitter in [0, span)."""
    h = splitmix32(((shard_id << 24) ^ (replica_id << 8) ^ seq) & 0xFFFFFFFF)
    return h % span


class Raft:
    """One raft replica's protocol state (reference: raft struct [U])."""

    # __slots__: tens of thousands of replicas per host — the instance
    # dict is pure overhead at that scale.  The last two slots are the
    # vector engine's residency-boundary markers (ops/engine.py sets
    # them with setattr; declared here so slots allow it).
    __slots__ = (
        "shard_id", "replica_id", "election_timeout", "heartbeat_timeout",
        "check_quorum", "pre_vote", "max_entries_per_replicate",
        "max_replicate_bytes", "max_in_mem_log_size", "term", "vote",
        "leader_id", "log", "remotes", "non_votings", "witnesses",
        "addresses", "role", "votes", "msgs", "ready_to_reads",
        "dropped_entries", "dropped_read_indexes", "read_index",
        "forwarded_reads", "leader_commit_hint",
        "election_tick", "heartbeat_tick", "randomized_election_timeout",
        "_timeout_seq", "leader_transfer_target", "pending_config_change",
        "is_leader_transfer_target", "snapshotting", "tick_count",
        "applied", "launched_non_voting", "launched_witness",
        "_cq_grace_at", "_term_lim_warned", "_campaign_sent_tick",
        "_boot_lease_grace",
    )

    def __init__(
        self,
        shard_id: int,
        replica_id: int,
        peers: Dict[int, str],
        non_votings: Optional[Dict[int, str]] = None,
        witnesses: Optional[Dict[int, str]] = None,
        election_timeout: int = 10,
        heartbeat_timeout: int = 1,
        check_quorum: bool = False,
        pre_vote: bool = False,
        log_reader: Optional[ILogReader] = None,
        state: Optional[State] = None,
        is_non_voting: bool = False,
        is_witness: bool = False,
        max_entries_per_replicate: Optional[int] = None,
        max_in_mem_log_size: int = 0,
    ):
        from .log import InMemLogReader

        self.shard_id = shard_id
        self.replica_id = replica_id
        self.election_timeout = election_timeout
        self.heartbeat_timeout = heartbeat_timeout
        self.check_quorum = check_quorum
        self.pre_vote = pre_vote
        self.max_entries_per_replicate = (
            max_entries_per_replicate
            if max_entries_per_replicate is not None
            else settings.Soft.max_entries_per_replicate
        )
        self.max_replicate_bytes = settings.Soft.max_replicate_bytes
        self.max_in_mem_log_size = max_in_mem_log_size

        self.term = 0
        self.vote = NO_NODE
        self.leader_id = NO_LEADER
        self.log = EntryLog(log_reader if log_reader is not None else InMemLogReader())

        self.remotes: Dict[int, Remote] = {}
        self.non_votings: Dict[int, Remote] = {}
        self.witnesses: Dict[int, Remote] = {}
        self.addresses: Dict[int, str] = {}

        self.role = RaftRole.FOLLOWER
        self.votes: Dict[int, bool] = {}
        self.msgs: List[Message] = []
        self.ready_to_reads: List[ReadyToRead] = []
        self.dropped_entries: List[Entry] = []
        self.dropped_read_indexes: List[SystemCtx] = []
        self.read_index = ReadIndex()
        # follower-side ReadIndex forwarding ledger: ctx key -> the
        # leader the confirmation round was sent to.  The readplane's
        # follower-linearizable path depends on a LEADERSHIP-CHANGE
        # ABORT: a confirmation obtained from a deposed leader must
        # never serve a read after a new leader may have committed past
        # it, so any leader change (new leader observed, leaderless
        # window, own candidacy) fails these ctxs fast via
        # dropped_read_indexes instead of leaving them to deadline GC
        # (docs/READPLANE.md "Follower-read safety").
        self.forwarded_reads: Dict[Tuple[int, int], int] = {}
        # the leader's commit index as LAST HEARD, uncapped — the
        # follower's own log.committed is min'd with its last index, so
        # a catching-up replica's local commit understates how far
        # behind its state is.  BOUNDED_STALENESS serving requires
        # applied >= this hint: fresh heartbeats alone must not let a
        # recovering follower serve months-old state as "bounded"
        # (docs/READPLANE.md).  Monotone per leadership; _reset floors
        # it back to the local commit.
        self.leader_commit_hint = 0

        self.election_tick = 0
        self.heartbeat_tick = 0
        self.randomized_election_timeout = election_timeout
        self._timeout_seq = 0

        self.leader_transfer_target = NO_NODE
        self.pending_config_change = False
        self.is_leader_transfer_target = False
        self.snapshotting = False
        self.tick_count = 0
        # applied index as reported by the RSM; used to gate config change
        self.applied = 0

        for pid, addr in (peers or {}).items():
            self.remotes[pid] = Remote(next=1)
            self.addresses[pid] = addr
        for pid, addr in (non_votings or {}).items():
            self.non_votings[pid] = Remote(next=1)
            self.addresses[pid] = addr
        for pid, addr in (witnesses or {}).items():
            self.witnesses[pid] = Remote(next=1)
            self.addresses[pid] = addr

        self.launched_non_voting = is_non_voting
        self.launched_witness = is_witness
        if is_non_voting:
            self.role = RaftRole.NON_VOTING
        elif is_witness:
            self.role = RaftRole.WITNESS

        if state is not None and not state.is_empty():
            self.term = state.term
            self.vote = state.vote
            self.log.committed = state.commit

        # tick at which the current (real) campaign's vote requests were
        # sent: the become_leader lease seed — granters reset their
        # election clocks no earlier than this (-1 = never campaigned)
        self._campaign_sent_tick = -1
        # restart hole in the vote-refusal lease (review finding):
        # leader_id is volatile, so a crash-restarted voter would grant
        # votes IMMEDIATELY even though, pre-crash, it refused them
        # inside a live leader's lease window — a challenger elected
        # through such votes breaks the leader's lease-read safety
        # argument.  A restored voter therefore refuses non-transfer
        # votes for its first election window (it cannot know how
        # recently it heard from a leader; one window over-covers).
        self._boot_lease_grace = (
            self.election_timeout
            if check_quorum and state is not None and not state.is_empty()
            else 0
        )

        self._reset_randomized_timeout()

    # ------------------------------------------------------------------
    # basic predicates
    # ------------------------------------------------------------------
    def is_leader(self) -> bool:
        return self.role == RaftRole.LEADER

    def is_follower(self) -> bool:
        return self.role == RaftRole.FOLLOWER

    def is_candidate(self) -> bool:
        return self.role == RaftRole.CANDIDATE

    def is_pre_candidate(self) -> bool:
        return self.role == RaftRole.PRE_CANDIDATE

    def is_non_voting(self) -> bool:
        return self.role == RaftRole.NON_VOTING

    def is_witness(self) -> bool:
        return self.role == RaftRole.WITNESS

    def is_self_removed(self) -> bool:
        return (
            self.replica_id not in self.remotes
            and self.replica_id not in self.non_votings
            and self.replica_id not in self.witnesses
        )

    def voting_members(self) -> Dict[int, Remote]:
        out = dict(self.remotes)
        out.update(self.witnesses)
        return out

    def quorum(self) -> int:
        return len(self.voting_members()) // 2 + 1

    def is_single_voter(self) -> bool:
        vm = self.voting_members()
        return len(vm) == 1 and self.replica_id in vm

    def all_remotes(self) -> Dict[int, Remote]:
        out = dict(self.remotes)
        out.update(self.non_votings)
        out.update(self.witnesses)
        return out

    def catching_up_peers(self) -> bool:
        """Leader-side: any peer whose match is still behind our log —
        used to BLOCK quiesce entry (entering quiesce mid-catch-up
        strands the follower: nobody generates the activity that would
        exit it).  reference: quiesce is activity-based in quiesce.go
        [U]; an active catch-up generates that activity there, but a
        stalled one must not idle the shard out here either."""
        if self.role != RaftRole.LEADER:
            return False
        last = self.log.last_index()
        for group in (self.remotes, self.non_votings, self.witnesses):
            for pid, rm in group.items():
                if pid != self.replica_id and rm.match < last:
                    return True
        return False

    def get_remote(self, replica_id: int) -> Optional[Remote]:
        r = self.remotes.get(replica_id)
        if r is None:
            r = self.non_votings.get(replica_id)
        if r is None:
            r = self.witnesses.get(replica_id)
        return r

    def rate_limited(self) -> bool:
        """In-mem log window above MaxInMemLogSize: new proposals should
        be refused with SystemBusy until apply/persist drains the window
        (reference: rate limiter + ErrSystemBusy [U])."""
        return (
            self.max_in_mem_log_size > 0
            and self.log.inmem.bytes > self.max_in_mem_log_size
        )

    def raft_state(self) -> State:
        return State(term=self.term, vote=self.vote, commit=self.log.committed)

    def committed_entry_in_current_term(self) -> bool:
        try:
            return self.log.term(self.log.committed) == self.term
        except (LogCompactedError, LogUnavailableError):
            return False

    # ------------------------------------------------------------------
    # time
    # ------------------------------------------------------------------
    def _reset_randomized_timeout(self) -> None:
        self._timeout_seq += 1
        self.randomized_election_timeout = self.election_timeout + election_jitter(
            self.shard_id, self.replica_id, self._timeout_seq, self.election_timeout
        )

    def time_for_election(self) -> bool:
        return self.election_tick >= self.randomized_election_timeout

    def tick(self) -> None:
        self.tick_count += 1
        if self.role == RaftRole.LEADER:
            self._leader_tick()
        else:
            self._nonleader_tick()

    def _leader_tick(self) -> None:
        self.election_tick += 1
        self.heartbeat_tick += 1
        if self.election_tick >= self.election_timeout:
            self.election_tick = 0
            if self.check_quorum:
                self.handle(Message(type=MessageType.CHECK_QUORUM))
                if self.role != RaftRole.LEADER:
                    # check-quorum stepped us down: no heartbeats at this term
                    return
            if self.leader_transfer_target != NO_NODE:
                # transfer did not complete within one election timeout
                self._abort_leader_transfer()
        if self.heartbeat_tick >= self.heartbeat_timeout:
            self.heartbeat_tick = 0
            self.broadcast_heartbeat()

    def _nonleader_tick(self) -> None:
        self.election_tick += 1
        if self.role in (RaftRole.NON_VOTING, RaftRole.WITNESS):
            if self.check_quorum and self.time_for_election():
                # probe whether the leader is still around
                self.election_tick = 0
                self._reset_randomized_timeout()
            return
        if self.time_for_election():
            self.election_tick = 0
            self.handle(Message(type=MessageType.ELECTION))

    # ------------------------------------------------------------------
    # role transitions
    # ------------------------------------------------------------------
    def _reset(self, term: int, keep_vote_on_same_term: bool = True) -> None:
        if self.term != term:
            self.term = term
            self.vote = NO_NODE
        self.leader_id = NO_LEADER
        self.election_tick = 0
        self.heartbeat_tick = 0
        self._reset_randomized_timeout()
        self.votes = {}
        self.leader_transfer_target = NO_NODE
        self.is_leader_transfer_target = False
        self.pending_config_change = False
        self.read_index.clear()
        self.drop_pending_read_indexes()
        self.leader_commit_hint = self.log.committed
        last = self.log.last_index()
        for pid, rm in self.all_remotes().items():
            rm.reset(last + 1)
            if pid == self.replica_id:
                rm.match = last

    def become_follower(self, term: int, leader_id: int) -> None:
        # a replica that joined with empty membership must keep its
        # configured tier until the config-change entry applies — a
        # "follower" window would let a witness campaign
        in_any = (
            self.replica_id in self.remotes
            or self.replica_id in self.non_votings
            or self.replica_id in self.witnesses
        )
        restore_role = (
            RaftRole.NON_VOTING
            if self.replica_id in self.non_votings
            or (not in_any and self.launched_non_voting)
            else RaftRole.WITNESS
            if self.replica_id in self.witnesses
            or (not in_any and self.launched_witness)
            else RaftRole.FOLLOWER
        )
        self.role = restore_role
        self._reset(term)
        self.leader_id = leader_id

    def become_pre_candidate(self) -> None:
        if self.role in (RaftRole.LEADER, RaftRole.NON_VOTING, RaftRole.WITNESS):
            raise RuntimeError(f"invalid pre-candidate transition from {self.role}")
        # prevote does not change term or vote
        role_term = self.term
        self.role = RaftRole.PRE_CANDIDATE
        self.votes = {}
        self.leader_id = NO_LEADER
        # prevote skips _reset, so the forwarded-read abort must fire
        # here: the election timeout that made us a pre-candidate is
        # exactly the "leader may be gone" signal the readplane's
        # follower-linearizable path must not read through
        self.drop_pending_read_indexes()
        self.election_tick = 0
        self._reset_randomized_timeout()
        assert self.term == role_term

    def become_candidate(self) -> None:
        if self.role in (RaftRole.LEADER, RaftRole.NON_VOTING, RaftRole.WITNESS):
            raise RuntimeError(f"invalid candidate transition from {self.role}")
        self.role = RaftRole.CANDIDATE
        self._reset(self.term + 1)
        self.vote = self.replica_id
        self.votes = {self.replica_id: True}

    def become_leader(self) -> None:
        if self.role not in (RaftRole.CANDIDATE, RaftRole.PRE_CANDIDATE, RaftRole.LEADER):
            raise RuntimeError(f"invalid leader transition from {self.role}")
        self.role = RaftRole.LEADER
        self._reset(self.term)
        self.leader_id = self.replica_id
        # a fresh leader starts with a FULL activity window (reference:
        # etcd-raft sets RecentActive=true at becomeLeader): the first
        # CheckQuorum otherwise races the first ack round-trip — under
        # the fused-tick engine a whole election window can elapse in
        # two launches, exactly one ack round-trip, and a hair-trigger
        # first check deposed every new leader forever
        # lease seed: anchor at the CAMPAIGN SEND tick, not the current
        # tick — the vote grants that elected us reset the granters'
        # election clocks at grant time, which is no earlier than the
        # vote-request send (anchoring at become_leader time would
        # overclaim by the whole vote round trip; review finding)
        seed = (
            self._campaign_sent_tick
            if self._campaign_sent_tick >= 0
            else self.tick_count if self.is_single_voter() else -1
        )
        for rm in self.all_remotes().values():
            rm.set_active()
            rm.last_resp_tick = max(rm.last_resp_tick, seed)
            rm.probe_queue.clear()  # fresh leadership, fresh probes
        self._compute_pending_config_change()
        # commit barrier: append an empty entry at the new term
        self._append_entries([Entry(type=EntryType.APPLICATION, cmd=b"")])
        _log.info(
            "[%d:%d] became leader term %d", self.shard_id, self.replica_id, self.term
        )

    def _compute_pending_config_change(self) -> None:
        """Scan uncommitted tail for in-flight config changes
        (reference: raft.getPendingConfigChangeCount [U])."""
        self.pending_config_change = False
        lo = self.log.committed + 1
        hi = self.log.last_index() + 1
        if lo >= hi:
            return
        try:
            for e in self.log._get_entries(lo, hi, 2**63):
                if e.type == EntryType.CONFIG_CHANGE:
                    self.pending_config_change = True
                    return
        except (LogCompactedError, LogUnavailableError):
            pass

    # ------------------------------------------------------------------
    # log append / commit
    # ------------------------------------------------------------------
    def _append_entries(self, entries: List[Entry]) -> None:
        last = self.log.last_index()
        stamped = []
        for i, e in enumerate(entries):
            stamped.append(
                Entry(
                    term=self.term,
                    index=last + 1 + i,
                    type=e.type,
                    key=e.key,
                    client_id=e.client_id,
                    series_id=e.series_id,
                    responded_to=e.responded_to,
                    cmd=e.cmd,
                )
            )
        self.log.append(stamped)
        me = self.get_remote(self.replica_id)
        if me is not None:
            me.try_update(self.log.last_index())
        if self.is_single_voter():
            self.try_commit()

    def try_commit(self) -> bool:
        """Quorum commit: sorted matchIndex reduction; commit only entries
        of the current term (reference: raft.tryCommit [U])."""
        matched = sorted(r.match for r in self.voting_members().values())
        qidx = matched[len(matched) - self.quorum()]
        if qidx <= self.log.committed:
            return False
        if not self.log.match_term(qidx, self.term):
            return False  # current-term-only commit rule
        self.log.commit_to(qidx)
        return True

    # ------------------------------------------------------------------
    # message send helpers
    # ------------------------------------------------------------------
    def _send(self, m: Message) -> None:
        m = Message(
            type=m.type,
            to=m.to,
            from_=self.replica_id,
            shard_id=self.shard_id,
            term=m.term if m.term else self.term,
            log_term=m.log_term,
            log_index=m.log_index,
            commit=m.commit,
            reject=m.reject,
            hint=m.hint,
            hint_high=m.hint_high,
            entries=m.entries,
            snapshot=m.snapshot,
        )
        self.msgs.append(m)

    # probe_queue bound: past this many unanswered probes, arms are
    # skipped (pops then anchor even older — the safe direction)
    _LEASE_PROBE_QUEUE_CAP = 128

    def _arm_lease_probe(self, rm) -> None:
        """A heartbeat/replicate to this peer is a lease probe: queue
        its send tick (FIFO; see Remote.last_resp_tick for the full
        anchoring contract and why the queue is never cleared)."""
        if len(rm.probe_queue) < self._LEASE_PROBE_QUEUE_CAP:
            rm.probe_queue.append(self.tick_count)

    def _anchor_lease_resp(self, rm) -> None:
        """A response proves contact no later than the answered probe's
        send (the follower's election clock reset at its receipt, which
        is >= that send under bounded skew).  Pop the FIFO head: the
        answered probe's send tick, or older when earlier probes or
        responses were lost — conservative either way.  Empty queue =>
        no anchor update — NEVER anchor at response receipt (review
        findings: receipt can lag the probe by unbounded queueing, and
        a cleared-then-re-armed slot mis-anchored a delayed response at
        a probe sent after it)."""
        if not rm.probe_queue:
            return
        probe = rm.probe_queue.pop(0)
        if probe > rm.last_resp_tick:
            rm.last_resp_tick = probe

    def broadcast_heartbeat(self, ctx: Optional[SystemCtx] = None) -> None:
        if ctx is None:
            ctx = self.read_index.peek_ctx()
        for pid, rm in sorted(self.all_remotes().items()):
            if pid == self.replica_id:
                continue
            self._arm_lease_probe(rm)
            self._send(
                Message(
                    type=MessageType.HEARTBEAT,
                    to=pid,
                    commit=min(rm.match, self.log.committed),
                    # log_index is unused by HEARTBEAT handling proper:
                    # it carries the UNCAPPED commit as an advisory for
                    # the follower's leader_commit_hint (the capped
                    # commit above understates for a behind follower,
                    # which would let its bounded reads serve stale
                    # state as fresh).  Never fed to commit_to.
                    log_index=self.log.committed,
                    hint=ctx.low if ctx else 0,
                    hint_high=ctx.high if ctx else 0,
                )
            )

    def broadcast_replicate(self) -> None:
        for pid in sorted(self.all_remotes().keys()):
            if pid == self.replica_id:
                continue
            self.send_replicate(pid)

    def send_replicate(self, to: int) -> None:
        rm = self.get_remote(to)
        if rm is None or rm.is_paused():
            return
        is_witness_target = to in self.witnesses
        next_i = rm.next
        try:
            prev_term = self.log.term(next_i - 1)
            entries = self.log.entries(next_i, self.max_replicate_bytes)
            if len(entries) > self.max_entries_per_replicate:
                entries = entries[: self.max_entries_per_replicate]
            if is_witness_target:
                entries = [self._to_witness_entry(e) for e in entries]
        except (LogCompactedError, LogUnavailableError):
            self._send_snapshot(to, rm)
            return
        self._arm_lease_probe(rm)
        self._send(
            Message(
                type=MessageType.REPLICATE,
                to=to,
                log_index=next_i - 1,
                log_term=prev_term,
                entries=tuple(entries),
                commit=self.log.committed,
            )
        )
        if entries:
            rm.progress(entries[-1].index)

    @staticmethod
    def _to_witness_entry(e: Entry) -> Entry:
        """Witnesses replicate metadata only (reference: witness handling in
        raft.go makeMetadataEntry [U])."""
        if e.type == EntryType.CONFIG_CHANGE:
            return e  # config changes are needed for membership tracking
        return Entry(term=e.term, index=e.index, type=EntryType.METADATA)

    def _send_snapshot(self, to: int, rm: Remote) -> None:
        ss = self.log.logdb.snapshot()
        if ss.is_empty():
            # nothing to send yet (snapshot still being produced); retry
            # later.  NO lease probe armed on this branch: nothing was
            # sent, so nothing will respond, and a phantom probe_queue
            # entry would shift every later anchor one probe older for
            # the rest of the leadership (review finding — the lease
            # would decay spuriously on shards with lagging followers)
            rm.become_wait()
            return
        if to in self.witnesses:
            ss = Snapshot(
                index=ss.index,
                term=ss.term,
                membership=ss.membership,
                dummy=True,
                witness=True,
                shard_id=self.shard_id,
            )
        # a snapshot send is a lease probe too: the follower answers it
        # with REPLICATE_RESP, and an un-armed send would let that
        # response pop a LATER probe's tick off the FIFO — shifting
        # subsequent anchors one probe too NEW (review finding)
        self._arm_lease_probe(rm)
        self._send(Message(type=MessageType.INSTALL_SNAPSHOT, to=to, snapshot=ss))
        rm.become_snapshot(ss.index)

    # ------------------------------------------------------------------
    # elections
    # ------------------------------------------------------------------
    def campaign(self, pre: bool, transfer: bool = False) -> None:
        if pre:
            self.become_pre_candidate()
            term = self.term + 1
            self.votes = {self.replica_id: True}
            if self._vote_quorum():
                # single-voter: skip straight to the real campaign
                self.campaign(pre=False, transfer=transfer)
                return
            mt = MessageType.REQUEST_PREVOTE
        else:
            # lease seed anchor: vote requests go out at THIS tick, so
            # any granter's election clock resets no earlier than it
            self._campaign_sent_tick = self.tick_count
            self.become_candidate()
            term = self.term
            if self._vote_quorum():
                self.become_leader()
                return
            mt = MessageType.REQUEST_VOTE
        for pid in sorted(self.voting_members().keys()):
            if pid == self.replica_id:
                continue
            self._send(
                Message(
                    type=mt,
                    to=pid,
                    term=term,
                    log_index=self.log.last_index(),
                    log_term=self.log.last_term(),
                    hint=self.replica_id if transfer else 0,
                )
            )

    def _vote_quorum(self) -> bool:
        granted = sum(1 for v in self.votes.values() if v)
        return granted >= self.quorum()

    def _vote_rejected(self) -> bool:
        rejected = sum(1 for v in self.votes.values() if not v)
        return rejected >= self.quorum()

    def _can_grant_vote(self, m: Message) -> bool:
        return (
            self.vote == NO_NODE
            or self.vote == m.from_
            or (m.type == MessageType.REQUEST_PREVOTE and m.term > self.term)
        )

    def _in_lease(self) -> bool:
        """CheckQuorum leader lease: reject votes while a live leader is
        known and the election timeout has not elapsed — and for the
        first election window after a restart from persisted state
        (``_boot_lease_grace``): leader_id does not survive restarts,
        so a rebooted voter must assume it was inside some leader's
        lease when it crashed."""
        if not self.check_quorum:
            return False
        if self.tick_count < self._boot_lease_grace:
            return True
        return (
            self.leader_id != NO_LEADER
            and self.election_tick < self.election_timeout
        )

    def anchor_quorum_evidence(self, tick: int) -> None:
        """Device-plane lease evidence (ROADMAP 4b): the engine proved
        a quorum of voter lanes active since ``tick`` (the device
        CheckQuorum window start — ops/hostplane.LeaseLanes), so raise
        every voting remote's ``last_resp_tick`` floor to it.  Raising
        ALL voters is exact for the lease: ``quorum_responded_tick``
        takes the quorum-th freshest, which becomes >= ``tick`` — the
        literal claim the device evidence makes — and monotone max
        keeps any fresher scalar-path probe anchors intact."""
        if self.role != RaftRole.LEADER:
            return
        for pid, rm in self.voting_members().items():
            if pid == self.replica_id:
                continue
            if tick > rm.last_resp_tick:
                rm.last_resp_tick = tick

    def quorum_responded_tick(self) -> int:
        """LEADER side of the lease (gateway lease reads): the most
        recent tick by which a QUORUM of voters (self included) had
        responded — the quorum-th freshest ``last_resp_tick``.  Every
        responder's own election clock was reset by the leader traffic
        it was responding to, so no challenger can win its vote for one
        election window past (roughly) that tick; the margin callers
        keep absorbs the cross-host tick skew (docs/GATEWAY.md
        "Lease-read safety").  -1 = no quorum evidence yet."""
        if self.role != RaftRole.LEADER:
            return -1
        vm = self.voting_members()
        if self.replica_id not in vm:
            # removed from the voter set but not yet stepped down: self
            # no longer counts toward the quorum, and the REMAINING
            # voters form a full quorum that can elect a challenger at
            # any time — no lease (review finding)
            return -1
        need = self.quorum() - 1  # self responds implicitly
        if need <= 0:
            return self.tick_count  # single-voter shard
        ticks = sorted(
            (
                rm.last_resp_tick
                for pid, rm in vm.items()
                if pid != self.replica_id
            ),
            reverse=True,
        )
        if len(ticks) < need:
            return -1
        return ticks[need - 1]

    def lease_remaining_ticks(self) -> int:
        """Ticks of leader lease left (0 when not leader / no
        CheckQuorum / no quorum evidence): one election window past the
        last quorum-responded tick.  A leader TRANSFER in flight also
        zeroes the lease: transfer votes (hint != 0) bypass the vote-
        refusal lease by design, so the target can be elected well
        inside the claimed window (review finding)."""
        if not self.check_quorum or self.role != RaftRole.LEADER:
            return 0
        if self.leader_transfer_target != NO_NODE:
            return 0
        base = self.quorum_responded_tick()
        if base < 0:
            return 0
        return max(0, base + self.election_timeout - self.tick_count)

    # ------------------------------------------------------------------
    # Step: the single entry point
    # ------------------------------------------------------------------
    def handle(self, m: Message) -> None:
        """Process one message (reference: raft.Handle/Step [U])."""
        if m.type == MessageType.LOCAL_TICK:
            self.tick()
            return
        if not self._on_message_term(m):
            return
        self._step(m)

    def _on_message_term(self, m: Message) -> bool:
        """Term comparison gate (reference: raft.onMessageTermNotMatched /
        etcd Step() term logic [U]).  Returns False if m is dropped."""
        if m.term == 0:
            return True  # local message
        if m.term > self.term:
            if m.type in (MessageType.REQUEST_VOTE, MessageType.REQUEST_PREVOTE):
                if self._in_lease() and m.hint == 0:
                    _log.info(
                        "[%d:%d] lease active, ignoring %s from %d at term %d",
                        self.shard_id,
                        self.replica_id,
                        m.type.name,
                        m.from_,
                        m.term,
                    )
                    return False
            if m.type == MessageType.REQUEST_PREVOTE:
                pass  # never change term on a prevote request
            elif m.type == MessageType.REQUEST_PREVOTE_RESP and not m.reject:
                pass  # winning a prevote at a future term; campaign handles it
            else:
                leader = m.from_ if m.is_leader_message() else NO_LEADER
                self.become_follower(m.term, leader)
            return True
        if m.term < self.term:
            if m.type in (
                MessageType.REPLICATE,
                MessageType.HEARTBEAT,
                MessageType.INSTALL_SNAPSHOT,
            ) and (self.check_quorum or self.pre_vote):
                # un-stick a deposed leader partitioned away: our higher term
                # in this response forces it to step down
                self._send(Message(type=MessageType.REPLICATE_RESP, to=m.from_))
            elif m.type == MessageType.REQUEST_PREVOTE:
                self._send(
                    Message(
                        type=MessageType.REQUEST_PREVOTE_RESP,
                        to=m.from_,
                        reject=True,
                        term=self.term,
                    )
                )
            return False
        return True

    def _step(self, m: Message) -> None:
        # local messages valid in any role
        if m.type == MessageType.ELECTION:
            self._handle_election(m)
            return
        if m.type == MessageType.REQUEST_VOTE:
            self._handle_request_vote(m)
            return
        if m.type == MessageType.REQUEST_PREVOTE:
            self._handle_request_prevote(m)
            return
        if self.role == RaftRole.LEADER:
            self._step_leader(m)
        elif self.role in (RaftRole.CANDIDATE, RaftRole.PRE_CANDIDATE):
            self._step_candidate(m)
        else:
            self._step_follower(m)

    # -- elections / votes ----------------------------------------------
    def _handle_election(self, m: Message) -> None:
        if self.role == RaftRole.LEADER:
            return
        if self.role in (RaftRole.NON_VOTING, RaftRole.WITNESS):
            return
        if self.replica_id not in self.remotes:
            return  # removed from membership
        transfer = m.hint == self.replica_id
        if not transfer and not self._has_config_applied():
            # avoid campaigning before the initial membership is applied
            pass
        if self.pre_vote and not transfer:
            self.campaign(pre=True, transfer=False)
        else:
            self.campaign(pre=False, transfer=transfer)

    def _has_config_applied(self) -> bool:
        return True

    def _handle_request_vote(self, m: Message) -> None:
        # witness may vote; non-voting may not
        if self.role == RaftRole.NON_VOTING:
            return
        up_to_date = self.log.up_to_date(m.log_index, m.log_term)
        grant = self._can_grant_vote(m) and up_to_date
        if grant:
            self.election_tick = 0
            self.vote = m.from_
        self._send(
            Message(
                type=MessageType.REQUEST_VOTE_RESP,
                to=m.from_,
                reject=not grant,
            )
        )

    def _handle_request_prevote(self, m: Message) -> None:
        if self.role == RaftRole.NON_VOTING:
            return
        up_to_date = self.log.up_to_date(m.log_index, m.log_term)
        grant = up_to_date and (m.term > self.term or self._can_grant_vote(m))
        # grant carries the candidate's future term; rejection our own term
        # (a higher rejection term forces the candidate back to follower)
        self._send(
            Message(
                type=MessageType.REQUEST_PREVOTE_RESP,
                to=m.from_,
                term=m.term if grant else self.term,
                reject=not grant,
            )
        )

    # -- leader ----------------------------------------------------------
    def _step_leader(self, m: Message) -> None:
        t = m.type
        if t == MessageType.PROPOSE:
            self._handle_propose(m)
        elif t == MessageType.CHECK_QUORUM:
            self._handle_check_quorum()
        elif t == MessageType.READ_INDEX:
            # from_ != self marks a request forwarded by a follower
            origin = m.from_ if m.from_ not in (0, self.replica_id) else self.replica_id
            self._handle_leader_read_index(m, from_=origin)
        elif t == MessageType.REPLICATE_RESP:
            self._handle_replicate_resp(m)
        elif t == MessageType.HEARTBEAT_RESP:
            self._handle_heartbeat_resp(m)
        elif t == MessageType.UNREACHABLE:
            self._handle_unreachable(m)
        elif t == MessageType.SNAPSHOT_STATUS:
            self._handle_snapshot_status(m)
        elif t == MessageType.SNAPSHOT_RECEIVED:
            self._handle_snapshot_received(m)
        elif t == MessageType.LEADER_TRANSFER:
            self._handle_leader_transfer(m)
        elif t == MessageType.LEADER_HEARTBEAT:
            self.broadcast_heartbeat()
        elif t == MessageType.REQUEST_VOTE_RESP:
            pass
        elif t == MessageType.REQUEST_PREVOTE_RESP:
            pass
        elif t == MessageType.TIMEOUT_NOW:
            pass
        elif t == MessageType.READ_INDEX_RESP:
            pass
        elif t == MessageType.REPLICATE:
            pass  # stale leader message at our own term is impossible
        elif t == MessageType.HEARTBEAT:
            pass
        elif t == MessageType.INSTALL_SNAPSHOT:
            pass
        else:
            _log.debug("leader dropping %s", t.name)

    def _handle_propose(self, m: Message) -> None:
        if self.leader_transfer_target != NO_NODE:
            self.dropped_entries.extend(m.entries)
            return
        entries = []
        for e in m.entries:
            if e.type == EntryType.CONFIG_CHANGE:
                if self.pending_config_change:
                    self.dropped_entries.append(e)
                    continue
                self.pending_config_change = True
            entries.append(e)
        if entries:
            self._append_entries(list(entries))
            self.broadcast_replicate()

    def _handle_check_quorum(self) -> None:
        active = 1  # self
        for pid, rm in self.voting_members().items():
            if pid == self.replica_id:
                rm.clear_active()
                continue
            if rm.is_active():
                active += 1
            rm.clear_active()
        if active < self.quorum():
            _log.warning(
                "[%d:%d] check-quorum failed, stepping down",
                self.shard_id,
                self.replica_id,
            )
            self.become_follower(self.term, NO_LEADER)

    def _handle_leader_read_index(self, m: Message, from_: int) -> None:
        ctx = SystemCtx(low=m.hint, high=m.hint_high)
        if self.is_witness():
            return
        if not self.committed_entry_in_current_term():
            # leader has not committed in its own term yet: unsafe to serve
            self.dropped_read_indexes.append(ctx)
            return
        if self.is_single_voter():
            if from_ == self.replica_id or from_ == 0:
                self.ready_to_reads.append(
                    ReadyToRead(index=self.log.committed, system_ctx=ctx)
                )
            else:
                self._send(
                    Message(
                        type=MessageType.READ_INDEX_RESP,
                        to=from_,
                        log_index=self.log.committed,
                        hint=ctx.low,
                        hint_high=ctx.high,
                    )
                )
            return
        self.read_index.add_request(self.log.committed, ctx, from_)
        self.broadcast_heartbeat(ctx)

    def _handle_replicate_resp(self, m: Message) -> None:
        rm = self.get_remote(m.from_)
        if rm is None:
            return
        rm.set_active()
        self._anchor_lease_resp(rm)
        if m.reject:
            # m.log_index = rejected prev index, m.hint = follower last index
            if rm.decrease(m.log_index, m.hint):
                self.send_replicate(m.from_)
            return
        paused = rm.is_paused()
        if rm.try_update(m.log_index):
            if (
                rm.state == RemoteState.SNAPSHOT
                and rm.match >= rm.snapshot_index
            ):
                rm.become_retry()
            if rm.state == RemoteState.RETRY:
                rm.become_replicate()
            if self.try_commit():
                self.broadcast_replicate()
            elif paused:
                self.send_replicate(m.from_)
            if (
                self.leader_transfer_target == m.from_
                and self.log.last_index() == rm.match
            ):
                self._send(Message(type=MessageType.TIMEOUT_NOW, to=m.from_))
        elif rm.state == RemoteState.SNAPSHOT and rm.match >= rm.snapshot_index:
            rm.become_retry()

    def _handle_heartbeat_resp(self, m: Message) -> None:
        rm = self.get_remote(m.from_)
        if rm is None:
            return
        rm.set_active()
        self._anchor_lease_resp(rm)
        rm.respond_to()
        if rm.match < self.log.last_index():
            self.send_replicate(m.from_)
        if (m.hint or m.hint_high) and (
            m.from_ in self.remotes or m.from_ in self.witnesses
        ):
            # only VOTING members count toward the read quorum: a
            # non-voting replica echoes heartbeat ctx hints too, and
            # counting it would confirm linearizable reads without a
            # real quorum (reference: etcd readOnly acks are tracked on
            # the voter progress set [U])
            self._read_index_confirm(SystemCtx(low=m.hint, high=m.hint_high), m.from_)

    def _read_index_confirm(self, ctx: SystemCtx, from_: int) -> None:
        done = self.read_index.confirm(ctx, from_, self.quorum())
        if not done:
            return
        for status in done:
            if status.from_ == NO_NODE or status.from_ == self.replica_id:
                self.ready_to_reads.append(
                    ReadyToRead(index=status.index, system_ctx=status.ctx)
                )
            else:
                self._send(
                    Message(
                        type=MessageType.READ_INDEX_RESP,
                        to=status.from_,
                        log_index=status.index,
                        hint=status.ctx.low,
                        hint_high=status.ctx.high,
                    )
                )

    def _handle_unreachable(self, m: Message) -> None:
        rm = self.get_remote(m.from_)
        if rm is None:
            return
        if rm.state == RemoteState.REPLICATE:
            rm.become_retry()

    def _handle_snapshot_status(self, m: Message) -> None:
        rm = self.get_remote(m.from_)
        if rm is None or rm.state != RemoteState.SNAPSHOT:
            return
        if m.reject:
            rm.clear_pending_snapshot()
        rm.become_wait()

    def _handle_snapshot_received(self, m: Message) -> None:
        rm = self.get_remote(m.from_)
        if rm is None or rm.state != RemoteState.SNAPSHOT:
            return
        rm.become_wait()

    def _handle_leader_transfer(self, m: Message) -> None:
        target = m.hint
        if target == self.replica_id:
            return
        rm = self.remotes.get(target)
        if rm is None:
            return  # target must be a voter (not witness/non-voting)
        if self.leader_transfer_target != NO_NODE:
            return
        self.leader_transfer_target = target
        self.election_tick = 0
        if rm.match == self.log.last_index():
            self._send(Message(type=MessageType.TIMEOUT_NOW, to=target))
        else:
            self.send_replicate(target)

    def _abort_leader_transfer(self) -> None:
        self.leader_transfer_target = NO_NODE

    # -- candidate --------------------------------------------------------
    def _step_candidate(self, m: Message) -> None:
        t = m.type
        if t == MessageType.PROPOSE:
            self.dropped_entries.extend(m.entries)
        elif t == MessageType.REPLICATE:
            self.become_follower(self.term, m.from_)
            self._handle_replicate(m)
        elif t == MessageType.HEARTBEAT:
            self.become_follower(self.term, m.from_)
            self._handle_heartbeat(m)
        elif t == MessageType.INSTALL_SNAPSHOT:
            self.become_follower(self.term, m.from_)
            self._handle_install_snapshot(m)
        elif t == MessageType.REQUEST_VOTE_RESP:
            if self.role != RaftRole.CANDIDATE:
                return
            self.votes[m.from_] = not m.reject
            if self._vote_quorum():
                self.become_leader()
                self.broadcast_replicate()
            elif self._vote_rejected():
                self.become_follower(self.term, NO_LEADER)
        elif t == MessageType.REQUEST_PREVOTE_RESP:
            if self.role != RaftRole.PRE_CANDIDATE:
                return
            if m.reject and m.term > self.term:
                self.become_follower(m.term, NO_LEADER)
                return
            self.votes[m.from_] = not m.reject
            if self._vote_quorum():
                self.campaign(pre=False)
            elif self._vote_rejected():
                self.become_follower(self.term, NO_LEADER)
        elif t == MessageType.READ_INDEX:
            self.dropped_read_indexes.append(SystemCtx(low=m.hint, high=m.hint_high))
        elif t == MessageType.TIMEOUT_NOW:
            pass
        else:
            _log.debug("candidate dropping %s", t.name)

    # -- follower ---------------------------------------------------------
    def _observe_leader(self, lid: int) -> None:
        """Follower saw leader traffic from ``lid``.  A SWITCH from a
        different known leader (possible without a local term bump when
        this replica missed the election entirely) aborts every
        confirmation round forwarded to the old leader — its answer may
        predate the new leader's commits (readplane leadership-change
        abort; the term-bump path is covered by _reset)."""
        if self.leader_id != lid and self.leader_id != NO_LEADER:
            self.drop_pending_read_indexes()
            self.leader_commit_hint = self.log.committed
        self.leader_id = lid

    def _step_follower(self, m: Message) -> None:
        t = m.type
        if t == MessageType.PROPOSE:
            if self.leader_id == NO_LEADER:
                self.dropped_entries.extend(m.entries)
                return
            # forward to leader
            self._send(
                Message(type=MessageType.PROPOSE, to=self.leader_id, entries=m.entries)
            )
        elif t == MessageType.REPLICATE:
            self.election_tick = 0
            self._observe_leader(m.from_)
            if m.commit > self.leader_commit_hint:
                self.leader_commit_hint = m.commit
            self._handle_replicate(m)
        elif t == MessageType.HEARTBEAT:
            self.election_tick = 0
            self._observe_leader(m.from_)
            # m.log_index = the leader's uncapped commit advisory (see
            # broadcast_heartbeat); m.commit is capped at our match
            hint = m.log_index if m.log_index > m.commit else m.commit
            if hint > self.leader_commit_hint:
                self.leader_commit_hint = hint
            self._handle_heartbeat(m)
        elif t == MessageType.INSTALL_SNAPSHOT:
            self.election_tick = 0
            self._observe_leader(m.from_)
            self._handle_install_snapshot(m)
        elif t == MessageType.READ_INDEX:
            if self.role in (RaftRole.NON_VOTING,):
                # non-voting replicas may serve linearizable reads through
                # the leader as well
                pass
            if self.is_witness():
                return
            if self.leader_id == NO_LEADER:
                self.dropped_read_indexes.append(
                    SystemCtx(low=m.hint, high=m.hint_high)
                )
                return
            self._send(
                Message(
                    type=MessageType.READ_INDEX,
                    to=self.leader_id,
                    hint=m.hint,
                    hint_high=m.hint_high,
                )
            )
            # ledger the in-flight confirmation round so a leadership
            # change aborts it (drop_pending_read_indexes).  Bounded: a
            # lost READ_INDEX_RESP leaves an entry behind until the
            # next leader change, so shed the oldest past a soft cap —
            # dropping early is safe (the future fails fast, client
            # retries) while a silent leak is not.
            fr = self.forwarded_reads
            fr[(m.hint, m.hint_high)] = self.leader_id
            if len(fr) > 4096:
                for key in list(fr)[:1024]:
                    del fr[key]
                    self.dropped_read_indexes.append(
                        SystemCtx(low=key[0], high=key[1])
                    )
        elif t == MessageType.READ_INDEX_RESP:
            self.forwarded_reads.pop((m.hint, m.hint_high), None)
            self.ready_to_reads.append(
                ReadyToRead(
                    index=m.log_index,
                    system_ctx=SystemCtx(low=m.hint, high=m.hint_high),
                )
            )
        elif t == MessageType.TIMEOUT_NOW:
            if self.role == RaftRole.FOLLOWER and self.replica_id in self.remotes:
                self.is_leader_transfer_target = True
                self.campaign(pre=False, transfer=True)
                self.is_leader_transfer_target = False
        elif t == MessageType.LEADER_TRANSFER:
            if self.leader_id != NO_LEADER:
                self._send(
                    Message(
                        type=MessageType.LEADER_TRANSFER,
                        to=self.leader_id,
                        hint=m.hint,
                    )
                )
        elif t == MessageType.REQUEST_VOTE_RESP:
            pass
        elif t == MessageType.REQUEST_PREVOTE_RESP:
            pass
        else:
            _log.debug("follower dropping %s", t.name)

    def _handle_replicate(self, m: Message) -> None:
        if m.log_index < self.log.committed:
            # stale: already committed past prev; reply with committed
            self._send(
                Message(
                    type=MessageType.REPLICATE_RESP,
                    to=m.from_,
                    log_index=self.log.committed,
                )
            )
            return
        ok, last_new = self.log.try_append(m.log_index, m.log_term, list(m.entries))
        if ok:
            self.log.commit_to(min(m.commit, last_new))
            self._send(
                Message(
                    type=MessageType.REPLICATE_RESP, to=m.from_, log_index=last_new
                )
            )
        else:
            _log.debug(
                "[%d:%d] rejected replicate prev(%d,t%d) from %d",
                self.shard_id,
                self.replica_id,
                m.log_index,
                m.log_term,
                m.from_,
            )
            self._send(
                Message(
                    type=MessageType.REPLICATE_RESP,
                    to=m.from_,
                    reject=True,
                    log_index=m.log_index,
                    hint=self.log.last_index(),
                )
            )

    def _handle_heartbeat(self, m: Message) -> None:
        self.log.commit_to(min(m.commit, self.log.last_index()))
        self._send(
            Message(
                type=MessageType.HEARTBEAT_RESP,
                to=m.from_,
                hint=m.hint,
                hint_high=m.hint_high,
            )
        )

    def _handle_install_snapshot(self, m: Message) -> None:
        ss = m.snapshot
        if self._restore(ss):
            self._send(
                Message(
                    type=MessageType.REPLICATE_RESP,
                    to=m.from_,
                    log_index=self.log.last_index(),
                )
            )
        else:
            self._send(
                Message(
                    type=MessageType.REPLICATE_RESP,
                    to=m.from_,
                    log_index=self.log.committed,
                )
            )

    def _restore(self, ss: Snapshot) -> bool:
        if ss.index <= self.log.committed:
            return False
        if self.log.match_term(ss.index, ss.term):
            # log already contains the snapshot point: just fast-forward
            self.log.commit_to(ss.index)
            return False
        self.log.restore(ss)
        self._restore_membership(ss.membership)
        return True

    def _restore_membership(self, membership: Membership) -> None:
        last = self.log.last_index()
        self.remotes = {}
        self.non_votings = {}
        self.witnesses = {}
        for pid, addr in membership.addresses.items():
            self.remotes[pid] = Remote(next=last + 1)
            self.addresses[pid] = addr
        for pid, addr in membership.non_votings.items():
            self.non_votings[pid] = Remote(next=last + 1)
            self.addresses[pid] = addr
        for pid, addr in membership.witnesses.items():
            self.witnesses[pid] = Remote(next=last + 1)
            self.addresses[pid] = addr
        if self.replica_id in self.non_votings:
            self.role = RaftRole.NON_VOTING
        elif self.replica_id in self.witnesses:
            self.role = RaftRole.WITNESS

    # ------------------------------------------------------------------
    # membership change (applied post-commit by the host)
    # ------------------------------------------------------------------
    def apply_config_change(self, cc: ConfigChange) -> None:
        """reference: raft.applyConfigChange [U] — called by the node after
        the config-change entry is committed and applied."""
        self.pending_config_change = False
        pid = cc.replica_id
        if cc.type == ConfigChangeType.ADD_REPLICA:
            self._add_replica(pid, cc.address)
        elif cc.type == ConfigChangeType.ADD_NON_VOTING:
            self._add_non_voting(pid, cc.address)
        elif cc.type == ConfigChangeType.ADD_WITNESS:
            self._add_witness(pid, cc.address)
        elif cc.type == ConfigChangeType.REMOVE_REPLICA:
            self._remove_replica(pid)

    def reject_config_change(self) -> None:
        self.pending_config_change = False

    def _add_replica(self, pid: int, address: str) -> None:
        self.addresses[pid] = address
        if pid in self.witnesses:
            raise RuntimeError("cannot promote a witness to voter")
        if pid in self.non_votings:
            # promotion keeps replication progress
            rm = self.non_votings.pop(pid)
            self.remotes[pid] = rm
            if pid == self.replica_id:
                self.role = RaftRole.FOLLOWER
            return
        if pid in self.remotes:
            return
        self.remotes[pid] = Remote(next=self.log.last_index() + 1)

    def _add_non_voting(self, pid: int, address: str) -> None:
        self.addresses[pid] = address
        if pid in self.remotes or pid in self.witnesses:
            raise RuntimeError("replica already a voter/witness")
        if pid in self.non_votings:
            return
        self.non_votings[pid] = Remote(next=self.log.last_index() + 1)

    def _add_witness(self, pid: int, address: str) -> None:
        self.addresses[pid] = address
        if pid in self.remotes or pid in self.non_votings:
            raise RuntimeError("replica already a voter/non-voting")
        if pid in self.witnesses:
            return
        self.witnesses[pid] = Remote(next=self.log.last_index() + 1)

    def _remove_replica(self, pid: int) -> None:
        self.remotes.pop(pid, None)
        self.non_votings.pop(pid, None)
        self.witnesses.pop(pid, None)
        self.addresses.pop(pid, None)
        if pid == self.replica_id:
            return
        if self.is_leader() and self.voting_members():
            if self.try_commit():
                self.broadcast_replicate()
            if self.leader_transfer_target == pid:
                self._abort_leader_transfer()

    # ------------------------------------------------------------------
    # output draining (used by Peer.get_update)
    # ------------------------------------------------------------------
    def drop_pending_read_indexes(self) -> None:
        """Fail every ReadIndex confirmation round this replica has
        forwarded to a leader (follower side of the readplane's
        leadership-change abort; the leader side's own pending table is
        ``read_index.clear()``).  Dropping is always safe — the caller's
        future fails fast and the client re-confirms against the current
        leader instead of trusting a deposed one's answer."""
        if self.forwarded_reads:
            for low, high in self.forwarded_reads:
                self.dropped_read_indexes.append(SystemCtx(low=low, high=high))
            self.forwarded_reads.clear()

    def drain_messages(self) -> List[Message]:
        out = self.msgs
        self.msgs = []
        return out

    def drain_ready_to_reads(self) -> List[ReadyToRead]:
        out = self.ready_to_reads
        self.ready_to_reads = []
        return out

    def drain_dropped(self):
        de, dr = self.dropped_entries, self.dropped_read_indexes
        self.dropped_entries, self.dropped_read_indexes = [], []
        return de, dr

    def get_membership(self) -> Membership:
        return Membership(
            addresses={
                pid: self.addresses.get(pid, "") for pid in self.remotes
            },
            non_votings={
                pid: self.addresses.get(pid, "") for pid in self.non_votings
            },
            witnesses={
                pid: self.addresses.get(pid, "") for pid in self.witnesses
            },
        )
