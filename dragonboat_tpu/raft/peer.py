"""Peer: the synchronous driver wrapper around ``Raft`` (RawNode-equivalent).

reference: internal/raft/peer.go [U].  ``get_update() -> pb.Update`` is the
entire I/O contract between the pure core and the host runtime; the TPU
step kernel reproduces exactly this function over batched state.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..pb import (
    ConfigChange,
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    State,
    EMPTY_STATE,
    SystemCtx,
    Update,
    UpdateCommit,
)
from .log import ILogReader
from .raft import Raft


class PeerInfo:
    def __init__(self, replica_id: int, address: str):
        self.replica_id = replica_id
        self.address = address


class Peer:
    __slots__ = ("raft", "prev_state")
    def __init__(self, raft: Raft):
        self.raft = raft
        self.prev_state: State = raft.raft_state()

    @classmethod
    def launch(
        cls,
        config,
        log_reader: ILogReader,
        state: Optional[State],
        addresses: Dict[int, str],
        non_votings: Optional[Dict[int, str]] = None,
        witnesses: Optional[Dict[int, str]] = None,
        initial: bool = True,
        new_node: bool = True,
    ) -> "Peer":
        """reference: peer.Launch [U]."""
        r = Raft(
            shard_id=config.shard_id,
            replica_id=config.replica_id,
            peers=dict(addresses),
            non_votings=dict(non_votings or {}),
            witnesses=dict(witnesses or {}),
            election_timeout=config.election_rtt,
            heartbeat_timeout=config.heartbeat_rtt,
            check_quorum=config.check_quorum,
            pre_vote=config.pre_vote,
            log_reader=log_reader,
            state=state,
            is_non_voting=config.is_non_voting,
            is_witness=config.is_witness,
            max_in_mem_log_size=config.max_in_mem_log_size,
        )
        return cls(r)

    # -- inputs ----------------------------------------------------------
    def tick(self) -> None:
        self.raft.handle(Message(type=MessageType.LOCAL_TICK))

    def quiesced_tick(self) -> None:
        # advances logical time without election side effects
        self.raft.tick_count += 1

    def handle(self, m: Message) -> None:
        self.raft.handle(m)

    def propose_entries(self, entries: List[Entry]) -> None:
        self.raft.handle(
            Message(type=MessageType.PROPOSE, entries=tuple(entries))
        )

    def propose_config_change(self, cc: ConfigChange, key: int) -> None:
        # positional binary, never pickle: this cmd replicates to every
        # peer and is decoded from the wire (transport/wire.py)
        from ..transport.wire import encode_config_change

        payload = encode_config_change(cc)
        self.raft.handle(
            Message(
                type=MessageType.PROPOSE,
                entries=(
                    Entry(type=EntryType.CONFIG_CHANGE, key=key, cmd=payload),
                ),
            )
        )

    def apply_config_change(self, cc: ConfigChange) -> None:
        self.raft.apply_config_change(cc)

    def reject_config_change(self) -> None:
        self.raft.reject_config_change()

    def read_index(self, ctx: SystemCtx) -> None:
        self.raft.handle(
            Message(type=MessageType.READ_INDEX, hint=ctx.low, hint_high=ctx.high)
        )

    def request_leader_transfer(self, target: int) -> None:
        self.raft.handle(Message(type=MessageType.LEADER_TRANSFER, hint=target))

    def report_unreachable_node(self, replica_id: int) -> None:
        self.raft.handle(
            Message(type=MessageType.UNREACHABLE, from_=replica_id)
        )

    def report_snapshot_status(self, replica_id: int, rejected: bool) -> None:
        self.raft.handle(
            Message(
                type=MessageType.SNAPSHOT_STATUS, from_=replica_id, reject=rejected
            )
        )

    def notify_raft_last_applied(self, applied: int) -> None:
        self.raft.applied = applied

    # -- outputs ---------------------------------------------------------
    def has_update(self, more_to_apply: bool = True) -> bool:
        r = self.raft
        if not r.raft_state().is_empty() and r.raft_state() != self.prev_state:
            return True
        if not r.log.inmem.snapshot.is_empty():
            return True
        return bool(
            r.log.entries_to_save()
            or r.msgs
            or (more_to_apply and r.log.has_entries_to_apply())
            or r.ready_to_reads
            or r.dropped_entries
            or r.dropped_read_indexes
        )

    def get_update(self, more_to_apply: bool = True, last_applied: int = 0) -> Update:
        """reference: peer.GetUpdate -> pb.Update [U]."""
        r = self.raft
        u = Update(shard_id=r.shard_id, replica_id=r.replica_id)
        u.state = r.raft_state()
        u.entries_to_save = r.log.entries_to_save()
        if more_to_apply:
            u.committed_entries = r.log.entries_to_apply()
        u.messages = r.drain_messages()
        u.ready_to_reads = r.drain_ready_to_reads()
        de, dr = r.drain_dropped()
        u.dropped_entries = de
        u.dropped_read_indexes = dr
        u.last_applied = last_applied
        if not r.log.inmem.snapshot.is_empty():
            u.snapshot = r.log.inmem.snapshot
        u.has_update = True
        u.update_commit = self._get_update_commit(u)
        return u

    def _get_update_commit(self, u: Update) -> UpdateCommit:
        uc = UpdateCommit(last_applied=u.last_applied)
        if u.committed_entries:
            uc = UpdateCommit(
                processed=u.committed_entries[-1].index,
                last_applied=u.last_applied,
            )
        if u.entries_to_save:
            uc = UpdateCommit(
                processed=uc.processed,
                last_applied=uc.last_applied,
                stable_log_index=u.entries_to_save[-1].index,
                stable_log_term=u.entries_to_save[-1].term,
            )
        if not u.snapshot.is_empty():
            uc = UpdateCommit(
                processed=max(uc.processed, u.snapshot.index),
                last_applied=uc.last_applied,
                stable_log_index=uc.stable_log_index,
                stable_log_term=uc.stable_log_term,
                stable_snapshot_index=u.snapshot.index,
            )
        return uc

    def commit(self, u: Update) -> None:
        """Advance cursors after the host has persisted/dispatched ``u``
        (reference: peer.Commit [U])."""
        self.prev_state = u.state
        self.raft.log.commit_update(u.update_commit)

    # -- introspection ----------------------------------------------------
    def leader_id(self) -> int:
        return self.raft.leader_id

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def term(self) -> int:
        return self.raft.term

    def committed(self) -> int:
        return self.raft.log.committed

    def has_entries_to_apply(self) -> bool:
        return self.raft.log.has_entries_to_apply()
