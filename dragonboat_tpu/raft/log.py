"""Raft log: in-memory tail + persisted prefix.

reference: internal/raft/logentry.go (entryLog), inmemory.go (inMemory) [U].

``InMemory`` holds the not-yet-persisted / not-yet-applied window;
``EntryLog`` is the unified view over ``InMemory`` and a persisted
``ILogReader`` (backed by the LogDB on the host, or a plain list in tests).
"""
from __future__ import annotations

from typing import List, Optional, Protocol, Sequence, Tuple

from ..pb import Entry, Snapshot, EMPTY_SNAPSHOT


class LogCompactedError(Exception):
    """Requested index has been compacted away."""


class LogUnavailableError(Exception):
    """Requested index is beyond the last known entry."""


class ILogReader(Protocol):
    """Read-only view of the persisted log (reference: the ILogDB-backed
    logReader, internal/logdb/logreader.go [U])."""

    def log_range(self) -> Tuple[int, int]:
        """(first_index, last_index) of available persisted entries; for an
        empty log returns (snapshot_index + 1, snapshot_index)."""
        ...

    def term(self, index: int) -> int: ...

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]: ...

    def snapshot(self) -> Snapshot: ...


class InMemLogReader:
    """An ILogReader over plain Python lists.

    Used by protocol unit tests and as the log view of the in-memory LogDB.
    Also supports the mutating half used by the host runtime (append /
    apply_snapshot / compact), mirroring internal/logdb/logreader.go [U].
    """

    def __init__(self, entries: Optional[Sequence[Entry]] = None):
        self._snapshot: Snapshot = EMPTY_SNAPSHOT
        # marker = index of _entries[0]; starts at 1 for a fresh log.
        self._marker = 1
        self._entries: List[Entry] = list(entries or [])
        if self._entries:
            self._marker = self._entries[0].index

    # -- ILogReader ------------------------------------------------------
    def log_range(self) -> Tuple[int, int]:
        first = max(self._marker, self._snapshot.index + 1)
        last = self._marker + len(self._entries) - 1
        if self._snapshot.index > last:
            last = self._snapshot.index
        return first, last

    def first_index(self) -> int:
        return self.log_range()[0]

    def last_index(self) -> int:
        return self.log_range()[1]

    def term(self, index: int) -> int:
        if index == self._snapshot.index and index > 0:
            return self._snapshot.term
        first, last = self.log_range()
        if index < first - 1:
            raise LogCompactedError(f"index {index} < first {first}")
        if index == first - 1:
            # the boundary: term known only via snapshot (handled above) or
            # a marker entry retained at compaction time
            if self._entries and index >= self._marker:
                return self._entries[index - self._marker].term
            if index == 0:
                return 0
            raise LogCompactedError(f"boundary index {index}")
        if index > last:
            raise LogUnavailableError(f"index {index} > last {last}")
        return self._entries[index - self._marker].term

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        first, last = self.log_range()
        if low < first:
            raise LogCompactedError(f"low {low} < first {first}")
        if high > last + 1:
            raise LogUnavailableError(f"high {high} > last+1 {last + 1}")
        out: List[Entry] = []
        size = 0
        for i in range(low, high):
            e = self._entries[i - self._marker]
            size += e.size_bytes()
            if out and size > max_size:
                break
            out.append(e)
        return out

    def snapshot(self) -> Snapshot:
        return self._snapshot

    # -- mutating half (host runtime) ------------------------------------
    def append(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        first_new = entries[0].index
        last_cur = self._marker + len(self._entries) - 1
        if first_new > last_cur + 1:
            raise ValueError(f"log gap: appending {first_new} after {last_cur}")
        if not self._entries:
            self._marker = first_new
            self._entries = list(entries)
            return
        if first_new <= self._marker:
            self._marker = first_new
            self._entries = list(entries)
        else:
            self._entries = self._entries[: first_new - self._marker] + list(entries)

    def apply_snapshot(self, ss: Snapshot) -> None:
        self._snapshot = ss
        self._marker = ss.index + 1
        self._entries = []

    def compact(self, to_index: int) -> None:
        """Drop entries <= to_index (term(to_index) stays resolvable only
        through the snapshot)."""
        first, last = self.log_range()
        if to_index < self._marker:
            return
        keep_from = min(to_index + 1, last + 1)
        self._entries = self._entries[keep_from - self._marker :]
        self._marker = keep_from


class InMemory:
    __slots__ = ("entries", "marker", "saved_to", "snapshot", "bytes")
    """The unpersisted/unapplied in-memory window of the log.

    reference: internal/raft/inmemory.go [U].  ``marker`` is the raft index
    of ``entries[0]``; ``saved_to`` the highest index known persisted.
    """

    def __init__(self, last_saved_index: int):
        self.marker = last_saved_index + 1
        self.entries: List[Entry] = []
        self.saved_to = last_saved_index
        self.snapshot: Snapshot = EMPTY_SNAPSHOT  # pending restore
        # byte size of the window — the MaxInMemLogSize rate-limit input
        # (reference: internal/server/rate.go InMemRateLimiter [U])
        self.bytes = 0

    def get_snapshot_index(self) -> Optional[int]:
        return None if self.snapshot.is_empty() else self.snapshot.index

    def get_entries(self, low: int, high: int) -> List[Entry]:
        if low > high or low < self.marker:
            raise LogCompactedError(f"inmem range [{low},{high}) marker {self.marker}")
        upper = self.marker + len(self.entries)
        if high > upper:
            raise LogUnavailableError(f"inmem high {high} > {upper}")
        return self.entries[low - self.marker : high - self.marker]

    def get_last_index(self) -> Optional[int]:
        if self.entries:
            return self.entries[-1].index
        return self.get_snapshot_index()

    def get_term(self, index: int) -> Optional[int]:
        if index >= self.marker and index < self.marker + len(self.entries):
            return self.entries[index - self.marker].term
        si = self.get_snapshot_index()
        if si is not None and index == si:
            return self.snapshot.term
        return None

    def merge(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        added = sum(e.size_bytes() for e in entries)
        first_new = entries[0].index
        last_cur = self.marker + len(self.entries) - 1
        if first_new == last_cur + 1:
            self.entries = self.entries + list(entries)
            self.bytes += added
        elif first_new <= self.marker:
            self.marker = first_new
            self.entries = list(entries)
            self.bytes = added
            self.saved_to = min(self.saved_to, first_new - 1)
        else:
            keep = first_new - self.marker
            self.bytes -= sum(e.size_bytes() for e in self.entries[keep:])
            self.entries = self.entries[:keep] + list(entries)
            self.bytes += added
            self.saved_to = min(self.saved_to, first_new - 1)

    def restore(self, ss: Snapshot) -> None:
        self.snapshot = ss
        self.marker = ss.index + 1
        self.entries = []
        self.bytes = 0
        self.saved_to = ss.index

    def entries_to_save(self) -> List[Entry]:
        if self.saved_to + 1 < self.marker:
            return []
        return self.entries[self.saved_to + 1 - self.marker :]

    def saved_log_to(self, index: int, term: int) -> None:
        t = self.get_term(index)
        if t is not None and t == term and index > self.saved_to:
            self.saved_to = index

    def saved_snapshot_to(self, index: int) -> None:
        si = self.get_snapshot_index()
        if si is not None and si == index:
            self.snapshot = EMPTY_SNAPSHOT

    def applied_log_to(self, index: int) -> None:
        """GC entries that are both persisted and applied."""
        keep_from = min(index, self.saved_to) + 1
        if keep_from <= self.marker:
            return
        last = self.marker + len(self.entries) - 1
        keep_from = min(keep_from, last + 1)
        dropped = self.entries[: keep_from - self.marker]
        self.bytes -= sum(e.size_bytes() for e in dropped)
        self.entries = self.entries[keep_from - self.marker :]
        self.marker = keep_from


class EntryLog:
    __slots__ = ("logdb", "inmem", "committed", "processed")
    """Unified log view with committed/processed cursors.

    reference: internal/raft/logentry.go (entryLog) [U].
    """

    def __init__(self, reader: ILogReader, committed: int = 0):
        self.logdb = reader
        first, last = reader.log_range()
        self.inmem = InMemory(last)
        self.committed = committed
        # everything below first-1 was snapshotted/applied before restart
        self.processed = first - 1

    # -- index bounds ----------------------------------------------------
    def first_index(self) -> int:
        si = self.inmem.get_snapshot_index()
        if si is not None:
            return si + 1
        return self.logdb.log_range()[0]

    def last_index(self) -> int:
        li = self.inmem.get_last_index()
        if li is not None:
            return li
        return self.logdb.log_range()[1]

    def term(self, index: int) -> int:
        t = self.inmem.get_term(index)
        if t is not None:
            return t
        first = self.first_index()
        if index == first - 1:
            ss = self.logdb.snapshot()
            if ss.index == index and index > 0:
                return ss.term
            if index == 0:
                return 0
        return self.logdb.term(index)

    def last_term(self) -> int:
        return self.term(self.last_index())

    def match_term(self, index: int, term: int) -> bool:
        if index == 0:
            return True
        try:
            return self.term(index) == term
        except (LogCompactedError, LogUnavailableError):
            return False

    def up_to_date(self, index: int, term: int) -> bool:
        lt = self.last_term()
        return term > lt or (term == lt and index >= self.last_index())

    # -- reads -----------------------------------------------------------
    def entries(self, low: int, max_size: int) -> List[Entry]:
        high = self.last_index() + 1
        if low >= high:
            return []
        return self._get_entries(low, high, max_size)

    def _get_entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        if low < self.first_index():
            raise LogCompactedError(f"low {low} < first {self.first_index()}")
        if high > self.last_index() + 1:
            raise LogUnavailableError(f"high {high}")
        out: List[Entry] = []
        if low < self.inmem.marker:
            out = self.logdb.entries(low, min(high, self.inmem.marker), max_size)
            got = len(out)
            if got < min(high, self.inmem.marker) - low:
                return out  # max_size hit
        if high > self.inmem.marker and (not out or out[-1].index + 1 >= self.inmem.marker):
            start = max(low, self.inmem.marker)
            tail = self.inmem.get_entries(start, high)
            size = sum(e.size_bytes() for e in out)
            for e in tail:
                size += e.size_bytes()
                if out and size > max_size:
                    break
                out.append(e)
        return out

    # -- writes ----------------------------------------------------------
    def append(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        if entries[0].index <= self.committed:
            raise RuntimeError(
                f"appending {entries[0].index} <= committed {self.committed}"
            )
        self.inmem.merge(entries)

    def try_append(
        self, prev_index: int, prev_term: int, entries: Sequence[Entry]
    ) -> Tuple[bool, int]:
        """Follower-side append with log-matching check.

        Returns (ok, last_new_index).
        """
        if not self.match_term(prev_index, prev_term):
            return False, 0
        last_new = prev_index + len(entries)
        conflict = self._find_conflict_index(entries)
        if conflict is not None:
            if conflict <= self.committed:
                raise RuntimeError(
                    f"conflict at {conflict} <= committed {self.committed}"
                )
            offset = conflict - (prev_index + 1)
            self.append(list(entries[offset:]))
        return True, last_new

    def _find_conflict_index(self, entries: Sequence[Entry]) -> Optional[int]:
        for e in entries:
            if not self.match_term(e.index, e.term):
                return e.index
        return None

    def commit_to(self, index: int) -> None:
        if index <= self.committed:
            return
        if index > self.last_index():
            raise RuntimeError(
                f"commit_to {index} > last_index {self.last_index()}"
            )
        self.committed = index

    def restore(self, ss: Snapshot) -> None:
        self.inmem.restore(ss)
        self.committed = ss.index
        self.processed = ss.index

    # -- update plumbing --------------------------------------------------
    def entries_to_save(self) -> List[Entry]:
        return self.inmem.entries_to_save()

    def has_entries_to_apply(self) -> bool:
        return self.committed > self.processed

    def entries_to_apply(self, limit: int = 2**63) -> List[Entry]:
        if not self.has_entries_to_apply():
            return []
        return self._get_entries(self.processed + 1, self.committed + 1, limit)

    def commit_update(self, uc) -> None:
        """Advance cursors after the host consumed an Update
        (reference: entryLog.commitUpdate [U])."""
        if uc.processed > 0:
            if uc.processed < self.processed or uc.processed > self.committed:
                raise RuntimeError(
                    f"invalid processed {uc.processed} "
                    f"(processed={self.processed} committed={self.committed})"
                )
            self.processed = uc.processed
            self.inmem.applied_log_to(uc.processed)
        if uc.stable_log_index > 0:
            self.inmem.saved_log_to(uc.stable_log_index, uc.stable_log_term)
        if uc.stable_snapshot_index > 0:
            self.inmem.saved_snapshot_to(uc.stable_snapshot_index)
            self.processed = max(self.processed, uc.stable_snapshot_index)
