"""Per-shard idle detection.

reference: quiesce.go (quiesceManager) [U].  After ``threshold`` ticks with
no activity the shard enters quiesce: no heartbeats are exchanged, ticks
become a counter increment — which is what lets one NodeHost hold millions
of idle groups.  Any message or proposal exits quiesce (with a burst of
LEADER_HEARTBEAT pokes so peers exit too).
"""
from __future__ import annotations

from ..pb import Message, MessageType


class QuiesceManager:
    def __init__(self, enabled: bool, election_timeout: int, threshold_mult: int = 10):
        self.enabled = enabled
        self.threshold = election_timeout * threshold_mult
        self.idle_ticks = 0
        self.quiesced = False
        self.exit_grace = 0

    def is_quiesced(self) -> bool:
        return self.quiesced

    def tick(self) -> bool:
        """Advance one tick; returns True if (now) quiesced."""
        if not self.enabled:
            return False
        self.idle_ticks += 1
        if self.exit_grace > 0:
            self.exit_grace -= 1
            return False
        if not self.quiesced and self.idle_ticks >= self.threshold:
            self.quiesced = True
        return self.quiesced

    def record_activity(self, msg_type: MessageType) -> bool:
        """Returns True if this activity exits quiesce (caller must then
        poke peers with LEADER_HEARTBEAT)."""
        if not self.enabled:
            return False
        if msg_type in (MessageType.HEARTBEAT, MessageType.HEARTBEAT_RESP):
            # heartbeats are not "activity": an idle-but-led group must
            # still be able to quiesce (reference: quiesceManager [U])
            if not self.quiesced:
                return False
        was = self.quiesced
        self.idle_ticks = 0
        if self.quiesced:
            self.quiesced = False
            self.exit_grace = self.threshold
        return was

    def new_to_quiesce(self) -> bool:
        return (
            self.enabled and not self.quiesced and self.idle_ticks >= self.threshold
        )
