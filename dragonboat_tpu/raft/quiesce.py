"""Per-shard idle detection.

reference: quiesce.go (quiesceManager) [U].  After ``threshold`` ticks with
no activity the shard enters quiesce: no heartbeats are exchanged, ticks
become a counter increment — which is what lets one NodeHost hold millions
of idle groups.  Any message or proposal exits quiesce (with a burst of
LEADER_HEARTBEAT pokes so peers exit too).
"""
from __future__ import annotations

from ..pb import Message, MessageType


class QuiesceManager:
    __slots__ = ("enabled", "threshold", "idle_ticks", "quiesced",
                 "exit_grace", "busy_ticks")
    def __init__(self, enabled: bool, election_timeout: int, threshold_mult: int = 10):
        self.enabled = enabled
        self.threshold = election_timeout * threshold_mult
        self.idle_ticks = 0
        self.quiesced = False
        self.busy_ticks = 0
        self.exit_grace = 0

    def is_quiesced(self) -> bool:
        return self.quiesced

    def tick(self, busy: bool = False, block: bool = False) -> bool:
        """Advance one tick; returns True if (now) quiesced.

        ``busy`` blocks ENTRY (and resets the idle window) without
        counting as wake-the-peers activity: a leader with a follower
        still behind must keep heartbeating/probing — entering quiesce
        mid-catch-up strands the follower forever, since nobody
        generates the activity that would exit it (r4 colocated chaos
        finding: heal -> cluster idles out before the slow follower
        caught up).

        ``block`` blocks entry UNBOUNDEDLY (no 3-window give-up): a
        shard with NO KNOWN LEADER must never quiesce — its election
        churn is the only thing that can produce a leader, and parking
        it freezes that churn forever (r5 finding: colocated elections
        are device-routed and invisible to this manager, so a shard
        still electing at the idle threshold quiesced+parked mid-churn
        and slept leaderless for good)."""
        if not self.enabled:
            return False
        if block and not self.quiesced:
            self.idle_ticks = 0
            self.busy_ticks = 0
            return False
        if busy and not self.quiesced:
            # BOUNDED hold: an active catch-up clears busy within a few
            # windows; a permanently dead peer never will, and holding
            # forever would defeat 'idle groups cost nothing' for every
            # shard with a down member (review finding).  After 3
            # windows the shard quiesces anyway — the returning peer's
            # first message is activity and wakes it.
            self.busy_ticks += 1
            if self.busy_ticks < 3 * self.threshold:
                self.idle_ticks = 0
                return False
        else:
            self.busy_ticks = 0
        self.idle_ticks += 1
        if self.exit_grace > 0:
            self.exit_grace -= 1
            return False
        if not self.quiesced and self.idle_ticks >= self.threshold:
            self.quiesced = True
        return self.quiesced

    def tick_n(self, n: int, busy: bool = False, block: bool = False) -> int:
        """Advance ``n`` ticks at once; returns the number of LIVE
        (non-quiesced) ticks.  Bit-equivalent to ``n`` sequential
        ``tick()`` calls with constant busy/block — the common cases are
        O(1) (multi-tick fusion hands the planner tens of ticks per row
        per launch; a per-tick method call loop was a measurable slice
        of the 50k-row host plane)."""
        if n <= 0:
            return 0
        if not self.enabled:
            return n
        if block and not self.quiesced:
            self.idle_ticks = 0
            self.busy_ticks = 0
            return n
        if self.quiesced and not busy and not block:
            # swallowed wholesale (same arithmetic the loop would do)
            self.idle_ticks += n
            self.busy_ticks = 0
            return 0
        if (
            not self.quiesced
            and not busy
            and self.exit_grace == 0
            and n < self.threshold - self.idle_ticks
        ):
            # far from the idle threshold: all live, no crossing
            self.idle_ticks += n
            self.busy_ticks = 0
            return n
        live = 0
        for _ in range(n):  # rare paths (grace, busy-hold, crossing)
            if not self.tick(busy=busy, block=block):
                live += 1
        return live

    def record_activity(self, msg_type: MessageType) -> bool:
        """Returns True if this activity exits quiesce (caller must then
        poke peers with LEADER_HEARTBEAT)."""
        if not self.enabled:
            return False
        if msg_type in (MessageType.HEARTBEAT, MessageType.HEARTBEAT_RESP):
            # heartbeats are NEVER "activity" — neither to stay awake
            # (an idle-but-led group must quiesce) nor to wake up (a
            # stale in-flight heartbeat from a not-yet-quiesced leader
            # must not wake a just-quiesced follower: that churns the
            # shard through wake/election cycles forever).  A quiesced
            # node still processes heartbeats in raft; quiesce only
            # gates its timers.
            return False
        was = self.quiesced
        self.idle_ticks = 0
        self.busy_ticks = 0
        if self.quiesced:
            self.quiesced = False
            self.exit_grace = self.threshold
        return was

    def quiesce_hint(self) -> None:
        """A peer announced it is entering quiesce (pb.Quiesce [U]): join
        it if this node is also idle, so the whole shard goes silent
        together (the leader stops heartbeating promptly)."""
        if not self.enabled or self.quiesced:
            return
        if self.exit_grace > 0:
            # recently woken by activity the hint sender didn't see;
            # entering now would flag quiesced while tick() still runs
            # live timers for the rest of the grace window — a
            # half-quiesced node whose election can fire into a silent
            # shard
            return
        if self.idle_ticks >= self.threshold // 2:
            self.quiesced = True

    def new_to_quiesce(self) -> bool:
        return (
            self.enabled and not self.quiesced and self.idle_ticks >= self.threshold
        )
