"""ReadIndex protocol bookkeeping on the leader.

reference: internal/raft/readindex.go [U].  Pending requests form an
ordered queue keyed by the client-supplied ``SystemCtx``; a quorum of
heartbeat-resp acks carrying the ctx confirms every request at or before
that ctx in queue order.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..pb import SystemCtx


@dataclass
class ReadStatus:
    index: int
    from_: int
    ctx: SystemCtx
    confirmed: Set[int] = field(default_factory=set)


class ReadIndex:
    __slots__ = ("pending", "queue")
    def __init__(self):
        self.pending: Dict[Tuple[int, int], ReadStatus] = {}
        self.queue: List[Tuple[int, int]] = []

    def clear(self) -> None:
        self.pending.clear()
        self.queue.clear()

    def add_request(self, index: int, ctx: SystemCtx, from_: int) -> None:
        key = (ctx.low, ctx.high)
        if key in self.pending:
            return
        self.pending[key] = ReadStatus(index=index, from_=from_, ctx=ctx)
        self.queue.append(key)

    def confirm(
        self, ctx: SystemCtx, from_: int, quorum: int
    ) -> Optional[List[ReadStatus]]:
        """Ack from ``from_`` for ``ctx``; on quorum, pop and return every
        request at or before ctx in queue order."""
        key = (ctx.low, ctx.high)
        status = self.pending.get(key)
        if status is None:
            return None
        status.confirmed.add(from_)
        # +1: the leader itself implicitly acks
        if len(status.confirmed) + 1 < quorum:
            return None
        done = 0
        out: List[ReadStatus] = []
        for k in self.queue:
            done += 1
            s = self.pending.pop(k)
            out.append(s)
            if k == key:
                break
        self.queue = self.queue[done:]
        return out

    def drop(self, ctx: SystemCtx) -> Optional[ReadStatus]:
        """Remove one pending request (e.g. the leader refused it)."""
        key = (ctx.low, ctx.high)
        s = self.pending.pop(key, None)
        if s is not None:
            self.queue.remove(key)
        return s

    def has_pending(self) -> bool:
        return bool(self.queue)

    def peek_ctx(self) -> Optional[SystemCtx]:
        if not self.queue:
            return None
        low, high = self.queue[-1]
        return SystemCtx(low=low, high=high)
