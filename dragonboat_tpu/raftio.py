"""Pluggable storage + transport contracts and listener event types.

reference: raftio/ (logdb.go, transport.go, rpc.go events) [U].
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .pb import Chunk, Entry, MessageBatch, Snapshot, State, Update


# ---------------------------------------------------------------------------
# LogDB (reference: raftio/logdb.go ILogDB [U])
# ---------------------------------------------------------------------------
@dataclass
class RaftState:
    """What ReadRaftState returns at restart."""

    state: State = field(default_factory=State)
    first_index: int = 0
    entry_count: int = 0


@dataclass
class NodeInfo:
    shard_id: int = 0
    replica_id: int = 0


class ILogDB(abc.ABC):
    """Persistent log storage contract.  ``save_raft_state`` is atomic for
    the whole batch of updates (entries + HardState + snapshot refs) and is
    the single fsync point of the write path."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def list_node_info(self) -> List[NodeInfo]: ...

    @abc.abstractmethod
    def save_bootstrap_info(
        self, shard_id: int, replica_id: int, bootstrap
    ) -> None: ...

    @abc.abstractmethod
    def get_bootstrap_info(self, shard_id: int, replica_id: int): ...

    @abc.abstractmethod
    def save_raft_state(self, updates: List[Update], worker_id: int) -> None: ...

    def save_state_lanes(
        self,
        shard_ids: List[int],
        replica_ids: List[int],
        terms: List[int],
        votes: List[int],
        commits: List[int],
        worker_id: int,
    ) -> None:
        """Batched hard-state-only save for the device merge tail's
        LANE rows (ops/hostplane.UpdateLanes): one call persists the
        (term, vote, commit) triple of many replicas with no per-row
        ``pb.Update`` carrier — the per-affected-row object walk was
        the residual host-plane wall at 50k-250k rows (ISSUE 13).

        Default implementation delegates through ``save_raft_state``
        with minimal state-only Updates, so every ILogDB — and any
        fault plane wrapped around its save path — behaves exactly as
        if the merge tail had emitted classic per-row updates.
        Implementations with a cheap hard-state slot (InMemLogDB)
        override with a direct batched write.  Atomicity/fsync
        contract is save_raft_state's.

        Optional slot protocol: a store may additionally expose
        ``state_lane_slot(shard_id, replica_id) -> int`` and
        ``save_state_slots(slots, terms, votes, commits, worker_id)``
        (vectorized scatter by pre-registered slot).  The engine
        detects the pair via ``getattr`` and caches slots per node
        (``Node.hs_lane_slot``); stores without it — including fault
        planes wrapped around the save path — get the list form
        above, so injected save faults still fire."""
        self.save_raft_state(
            [
                Update(
                    shard_id=s,
                    replica_id=r,
                    state=State(term=t, vote=v, commit=c),
                    has_update=True,
                )
                for s, r, t, v, c in zip(
                    shard_ids, replica_ids, terms, votes, commits
                )
            ],
            worker_id,
        )

    @abc.abstractmethod
    def read_raft_state(
        self, shard_id: int, replica_id: int, last_index: int
    ) -> Optional[RaftState]: ...

    @abc.abstractmethod
    def iterate_entries(
        self,
        shard_id: int,
        replica_id: int,
        low: int,
        high: int,
        max_size: int,
    ) -> List[Entry]: ...

    @abc.abstractmethod
    def term(self, shard_id: int, replica_id: int, index: int) -> Optional[int]: ...

    @abc.abstractmethod
    def remove_entries_to(
        self, shard_id: int, replica_id: int, index: int
    ) -> None: ...

    @abc.abstractmethod
    def compact_entries_to(
        self, shard_id: int, replica_id: int, index: int
    ) -> None: ...

    @abc.abstractmethod
    def save_snapshots(self, updates: List[Update]) -> None: ...

    @abc.abstractmethod
    def get_snapshot(self, shard_id: int, replica_id: int) -> Snapshot: ...

    @abc.abstractmethod
    def remove_node_data(self, shard_id: int, replica_id: int) -> None: ...

    @abc.abstractmethod
    def import_snapshot(self, snapshot: Snapshot, replica_id: int) -> None: ...


# ---------------------------------------------------------------------------
# Transport (reference: raftio/transport.go ITransport [U])
# ---------------------------------------------------------------------------
class IConnection(abc.ABC):
    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def send_message_batch(self, batch: MessageBatch) -> None: ...


class ISnapshotConnection(abc.ABC):
    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def send_chunk(self, chunk: Chunk) -> None: ...

    def query_resume(self, probe: Chunk) -> int:
        """Ask the receiver for its receive cursor on the stream whose
        identity ``probe`` carries (transport.chunk.resume_probe): the
        next chunk offset it needs, 0 for restart-from-scratch.
        Transports without a resume channel keep the default — a
        reconnected sender then restarts at chunk 0 and the receiver's
        idempotent re-delivery path discards what it already wrote."""
        return 0


MessageHandler = Callable[[MessageBatch], None]
ChunkHandler = Callable[[Chunk], bool]


class ITransport(abc.ABC):
    """reference: raftio.ITransport (v3 IRaftRPC) [U].

    Implementations SHOULD pass every outbound payload through
    ``self.fault_injector.on_wire(source, target, payload)`` when the
    attribute is non-None — that is the contract that lets the unified
    nemesis (faults.FaultController) inject partitions, loss, delay,
    duplication, reordering and chunk corruption on any transport
    (see docs/FAULTS.md).
    """

    # the unified fault plane; None in production.  fault_source is the
    # identity to report as `source` to on_wire — the Transport wrapper
    # sets it to the RAFT address (what fault plans target), which may
    # differ from a bind/listen address
    fault_injector = None
    fault_source = None

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def get_connection(self, target: str) -> IConnection: ...

    @abc.abstractmethod
    def get_snapshot_connection(self, target: str) -> ISnapshotConnection: ...


# ---------------------------------------------------------------------------
# Event listener payloads (reference: raftio/events.go [U])
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class LeaderInfo:
    shard_id: int
    replica_id: int
    term: int
    leader_id: int


@dataclass(frozen=True)
class NodeInfoEvent:
    shard_id: int
    replica_id: int


@dataclass(frozen=True)
class SnapshotInfo:
    shard_id: int
    replica_id: int
    from_: int
    index: int


@dataclass(frozen=True)
class EntryInfo:
    shard_id: int
    replica_id: int
    index: int


@dataclass(frozen=True)
class ConnectionInfo:
    address: str
    snapshot_connection: bool


@dataclass(frozen=True)
class BalanceMoveInfo:
    """One rebalancing move transition (balance/ control plane).

    ``step`` is the move state the transition refers to: ``plan``,
    ``add``, ``catchup``, ``catchup_progress``, ``transfer``,
    ``remove``, ``rollback``.  ``src``/``dst`` are host keys (raft
    addresses); for pure leadership transfers ``replica_id`` is the
    transfer target.  ``detail`` carries step-specific context — for
    ``catchup_progress`` the live ``snapshot_stream_*`` numbers
    (bytes moved, resume count, ETA) so operators watching move events
    see TRANSFER progress instead of a blind applied-index poll.
    """

    shard_id: int
    kind: str
    src: str
    dst: str
    replica_id: int
    step: str = ""
    detail: str = ""


class IRaftEventListener(abc.ABC):
    @abc.abstractmethod
    def leader_updated(self, info: LeaderInfo) -> None: ...


class ISystemEventListener:
    """Optional callbacks; default implementations are no-ops so users
    override only what they need (reference: ISystemEventListener [U])."""

    def node_host_shutting_down(self) -> None: ...

    def node_ready(self, info: NodeInfoEvent) -> None: ...

    def node_unloaded(self, info: NodeInfoEvent) -> None: ...

    def membership_changed(self, info: NodeInfoEvent) -> None: ...

    def connection_established(self, info: ConnectionInfo) -> None: ...

    def connection_failed(self, info: ConnectionInfo) -> None: ...

    def send_snapshot_started(self, info: SnapshotInfo) -> None: ...

    def send_snapshot_completed(self, info: SnapshotInfo) -> None: ...

    def send_snapshot_aborted(self, info: SnapshotInfo) -> None: ...

    def snapshot_received(self, info: SnapshotInfo) -> None: ...

    def snapshot_recovered(self, info: SnapshotInfo) -> None: ...

    def snapshot_created(self, info: SnapshotInfo) -> None: ...

    def snapshot_compacted(self, info: SnapshotInfo) -> None: ...

    def log_compacted(self, info: EntryInfo) -> None: ...

    def log_db_compacted(self, info: EntryInfo) -> None: ...

    # -- balance/ control-plane transitions (no reference equivalent:
    # upstream stops at mechanism and leaves placement to the user) --
    def balance_move_started(self, info: BalanceMoveInfo) -> None: ...

    def balance_move_step(self, info: BalanceMoveInfo) -> None: ...

    def balance_move_completed(self, info: BalanceMoveInfo) -> None: ...

    def balance_move_failed(self, info: BalanceMoveInfo) -> None: ...

    def balance_move_rolled_back(self, info: BalanceMoveInfo) -> None: ...
