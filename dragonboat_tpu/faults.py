"""Unified deterministic fault-injection subsystem (the nemesis).

reference: the drummer/monkeytest chaos methodology [U] — long-running
clusters shaken by partitions, message loss, disk faults and crash
cycles, with invariant checks after every heal.  This module replaces
the three ad-hoc injection points that grew organically (the in-proc
transport's ``drop_hook``, ``StrictMemFS.fault_hook`` and the tan
LogDB's ``fault_hook``) with ONE seeded, declarative fault plane that
every layer consumes:

* **wire** — both raw transports (``transport/inproc.py``,
  ``transport/tcp.py``) pass every outbound ``MessageBatch``/``Chunk``
  through :meth:`FaultController.on_wire`, which applies symmetric or
  asymmetric partitions, probabilistic drop / delay / duplicate /
  reorder, and snapshot-chunk corruption.
* **storage** — ``StrictMemFS`` and the tan WAL consult
  :meth:`on_fs_op` before data-touching operations; active fault
  windows raise injected fsync / torn-write errors.
* **engine** — the device step engines consult
  :meth:`on_engine_step` per row per launch; an active ``escalate``
  fault forces the kernel-escalation recovery path (discard device
  effects, replay on the scalar).
* **process** — ``crash`` faults call harness-registered kill/restart
  callbacks, so replica crash-restart cycles ride the same schedule.
* **balance** — the rebalancing move executor (``balance/executor.py``)
  consults :meth:`on_balance_step` before every step of a move;
  ``balance_abort`` kills the move mid-sequence (forcing the rollback
  path) and ``balance_stall`` stretches a step so other planes can
  strike while the move is in flight.
* **stream** — the snapshot stream jobs (``transport/transport.py``)
  consult :meth:`on_snapshot_stream` per outbound chunk;
  ``snapshot_stream_kill`` raises mid-transfer (the streamer dies and
  the retry must RESUME from the receiver's cursor — docs/BIGSTATE.md)
  and ``snapshot_stream_stall`` stretches the transfer so other planes
  can strike while a laggard is mid-catch-up.
* **churn** — drummer-style scheduled churn (:meth:`install_churn`):
  ``leader_kill`` samples and kills the CURRENT leader of a shard,
  ``leader_transfer`` forces leadership to another voter,
  ``member_cycle`` adds/removes a non-voting member mid-traffic and
  ``balance_move`` races one ``Balancer`` move against the schedule —
  each optionally followed by a per-event recovery-SLA assert
  (re-election bound + commit continuity; misses collect in
  :attr:`FaultController.churn_violations`).  The linearizability
  audit harness (``dragonboat_tpu.audit``, docs/AUDIT.md) records
  client histories while this plane runs and checks them offline.

Determinism contract: a plan is executed strictly in schedule order by
one nemesis thread, and :attr:`FaultController.event_log` records each
activation/heal with its plan step index and parameters — NO wall-clock
values — so the same seed and plan produce a byte-identical event log
on every run.  Per-payload decisions (e.g. which messages a 30%% drop
window actually eats) come from per-lane RNGs seeded from
``(seed, kind, source, target, payload_type)``; their sequence is
deterministic per lane even though cross-lane interleaving is
scheduling-dependent.

Seed-replay workflow: every chaos failure prints ``controller.seed``;
re-running with that seed replays the identical fault schedule (see
docs/FAULTS.md).
"""
from __future__ import annotations

import math
import threading
import time
import zlib
from dataclasses import dataclass, field
from random import Random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .logger import get_logger

_log = get_logger("faults")

# operations on_fs_op treats as durability points
_SYNC_OPS = ("sync", "sync_dir", "wal_append")
# operations that mutate file data (torn-write / write-error windows)
_WRITE_OPS = ("write", "create", "truncate", "rename", "unlink", "wal_append")

WIRE_KINDS = (
    "partition",
    "drop",
    "delay",
    "duplicate",
    "reorder",
    "chunk_corrupt",
    # directional kinds: targets are "src->dst" PAIRS (asym_pair), not
    # source addresses — A's sends to B suffer while B's to A flow
    # clean, the one-way partition / one-way delay the symmetric kinds
    # above cannot express (A sees B but B drops A).  Both the in-proc
    # and the TCP transport consume them through the same on_wire hook.
    "asym_drop",
    "asym_delay",
)
ASYM_KINDS = ("asym_drop", "asym_delay")


def asym_pair(src: str, dst: str) -> str:
    """Canonical target form for the directional wire kinds."""
    return f"{src}->{dst}"
FS_KINDS = ("fsync_err", "torn_write", "write_err")
ENGINE_KINDS = ("escalate",)
PROCESS_KINDS = ("crash",)
# balance plane: kill a rebalancing move mid-sequence.  ``balance_abort``
# makes the executor's next fault-point check raise (targets = shard
# ids, empty = every shard); ``balance_stall`` sleeps ``delay`` seconds
# at the fault point, widening the window in which wire/process faults
# can land mid-move.
BALANCE_KINDS = ("balance_abort", "balance_stall")
# churn plane (drummer-style scheduled churn; see install_churn):
# ``leader_kill`` samples the CURRENT leader of a target shard and
# kills its host replica through the harness kill handler (duration =
# downtime before the restart handler fires); ``leader_transfer``
# forces leadership to a deterministically-drawn other voter;
# ``member_cycle`` adds a fresh non-voting member mid-traffic and
# removes it again at heal; ``balance_move`` races one Balancer move
# (balance/) against whatever else the schedule has active.  Targets
# are shard ids (empty = one drawn from the installed churn shards).
# The SCHEDULE stays byte-identical per seed (event_log records only
# the declarative faults); runtime-sampled victims go to ``churn_log``.
CHURN_KINDS = (
    "leader_kill",
    "leader_transfer",
    "member_cycle",
    "balance_move",
)
# snapshot-stream plane (the big-state nemesis; docs/BIGSTATE.md):
# ``snapshot_stream_kill`` raises inside the sender's stream job
# mid-transfer (the streamer thread dies exactly as a torn connection
# would) — the transport's bounded-retry path must RESUME from the
# receiver's cursor, not restart from zero; ``snapshot_stream_stall``
# sleeps ``delay`` per chunk, stretching the transfer so leader churn /
# wire faults can land while a laggard is mid-catch-up.  Targets are
# SENDER transport addresses (wire-kind convention; empty = any sender)
# or ``"dst:<addr>"`` entries scoping by RECEIVER — the witness/dummy
# chaos schedule needs "every stream going TO this replica" regardless
# of which live voter happens to lead (and therefore send) that round.
STREAM_KINDS = ("snapshot_stream_kill", "snapshot_stream_stall")
STREAM_DST_PREFIX = "dst:"
ALL_KINDS = (
    WIRE_KINDS + FS_KINDS + ENGINE_KINDS + PROCESS_KINDS + BALANCE_KINDS
    + CHURN_KINDS + STREAM_KINDS
)


class TornWriteError(OSError):
    """Raised by ``on_fs_op`` inside a torn-write window.  ``keep``
    tells a cooperating FS what fraction of the write to apply before
    failing (StrictMemFS persists that prefix, reproducing a torn
    final write without a full crash)."""

    def __init__(self, keep: float):
        super().__init__("nemesis: injected torn write")
        self.keep = keep


@dataclass(frozen=True)
class Fault:
    """One declarative fault.

    ``at``/``duration`` are seconds from plan start (one-shot faults
    use duration 0; ``crash`` interprets duration as downtime before
    the restart callback fires).  ``targets`` scopes the fault:
    transport addresses for wire kinds (a ``partition``'s targets are
    side A), component keys for fs kinds, shard ids for ``escalate``,
    harness keys for ``crash``; empty = every installed component.
    ``p`` is the per-event probability inside the window.
    """

    kind: str
    at: float = 0.0
    duration: float = 0.0
    targets: Tuple = ()
    p: float = 1.0
    delay: float = 0.05  # kind="delay": seconds each affected send stalls
    both_ways: bool = True  # kind="partition": symmetric vs A->rest only

    def __post_init__(self):
        if self.kind not in ALL_KINDS:
            raise ValueError(f"unknown fault kind: {self.kind!r}")

    def describe(self) -> str:
        return (
            f"{self.kind}(at={self.at:g},dur={self.duration:g},"
            f"targets={tuple(self.targets)!r},p={self.p:g},"
            f"delay={self.delay:g},both_ways={self.both_ways})"
        )


@dataclass
class FaultPlan:
    """An ordered fault schedule.  ``describe()`` is the canonical
    byte-form used by the determinism tests — two plans are the same
    schedule iff their describe() strings are equal."""

    faults: List[Fault] = field(default_factory=list)

    def describe(self) -> str:
        return "\n".join(f.describe() for f in self.faults)

    @staticmethod
    def randomized(
        seed: int,
        *,
        addrs: Sequence[str],
        fs_keys: Sequence = (),
        crash_keys: Sequence = (),
        shards: Sequence[int] = (),
        churn_shards: Sequence[int] = (),
        stream_addrs: Sequence[str] = (),
        stream_recv_addrs: Sequence[str] = (),
        asym_pairs: Sequence[str] = (),
        balance_shards: Sequence[int] = (),
        rounds: int = 8,
        mean_gap: float = 0.8,
        mean_duration: float = 0.8,
    ) -> "FaultPlan":
        """Generate a randomized-but-deterministic plan: same arguments
        and seed produce the identical plan (the soak entry point's
        replay contract).  ``churn_shards`` adds the churn plane's
        leader kills / transfers / membership cycles to the kind pool
        (the consumer must have called ``install_churn``);
        ``stream_addrs`` adds the snapshot-stream plane (kill/stall the
        streamer of the named sender addresses) — opt-in so existing
        seeded schedules stay byte-identical.  ``stream_recv_addrs``
        widens the stream plane's target pool with RECEIVER-scoped
        entries (``dst:<addr>``): a schedule can then strike every
        stream going TO a witness/dummy or laggard replica no matter
        which voter is the current sender; passing only
        ``stream_addrs`` keeps the drawn plan byte-identical to
        pre-``stream_recv_addrs`` trees (same pool, same draws).
        ``asym_pairs`` (``asym_pair(src, dst)`` strings) adds the
        directional wire kinds to the pool — same opt-in discipline:
        omitting it keeps every pre-existing seeded schedule
        byte-identical.  ``balance_shards`` adds ``balance_move``
        (race ONE planner move against the schedule; the consumer must
        have called ``install_balancer``) with its own shard target
        pool — opt-in like every knob before it."""
        rng = Random(seed)
        addrs = list(addrs)
        stream_pool = list(stream_addrs) + [
            STREAM_DST_PREFIX + a for a in stream_recv_addrs
        ]
        kinds = ["partition", "drop", "delay", "duplicate", "reorder"]
        if fs_keys:
            kinds += ["fsync_err", "torn_write"]
        if crash_keys:
            kinds.append("crash")
        if shards:
            kinds.append("escalate")
        if churn_shards:
            kinds += ["leader_kill", "leader_transfer", "member_cycle"]
        if stream_pool:
            kinds += ["snapshot_stream_kill", "snapshot_stream_stall"]
        if asym_pairs:
            kinds += ["asym_drop", "asym_delay"]
        if balance_shards:
            kinds.append("balance_move")
        t = 0.0
        faults: List[Fault] = []
        for _ in range(rounds):
            t += rng.uniform(0.2, 2 * mean_gap)
            kind = rng.choice(kinds)
            dur = rng.uniform(0.3, 2 * mean_duration)
            if kind == "partition":
                side = tuple(
                    sorted(rng.sample(addrs, rng.choice((1, len(addrs) // 2 or 1))))
                )
                faults.append(Fault(kind, at=t, duration=dur, targets=side))
            elif kind in ("drop", "delay", "duplicate", "reorder"):
                src = tuple(sorted(rng.sample(addrs, rng.randrange(1, len(addrs) + 1))))
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=dur,
                        targets=src,
                        p=round(rng.uniform(0.1, 0.6), 3),
                        delay=round(rng.uniform(0.01, 0.1), 3),
                    )
                )
            elif kind in ("fsync_err", "torn_write"):
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=dur,
                        targets=(rng.choice(list(fs_keys)),),
                        p=round(rng.uniform(0.3, 0.9), 3),
                    )
                )
            elif kind == "crash":
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=max(0.4, dur),
                        targets=(rng.choice(list(crash_keys)),),
                    )
                )
            elif kind in CHURN_KINDS:
                pool = balance_shards if kind == "balance_move" else churn_shards
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=max(0.4, dur) if kind != "leader_transfer" else 0.0,
                        targets=(rng.choice(list(pool)),),
                    )
                )
            elif kind in STREAM_KINDS:
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=dur,
                        targets=(rng.choice(stream_pool),),
                        p=round(rng.uniform(0.05, 0.3), 3),
                        delay=round(rng.uniform(0.01, 0.1), 3),
                    )
                )
            elif kind in ASYM_KINDS:
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=dur,
                        targets=(rng.choice(list(asym_pairs)),),
                        p=round(rng.uniform(0.2, 0.8), 3),
                        delay=round(rng.uniform(0.01, 0.1), 3),
                    )
                )
            else:  # escalate
                faults.append(
                    Fault(
                        kind,
                        at=t,
                        duration=dur,
                        targets=tuple(sorted(rng.sample(list(shards), 1))),
                        p=round(rng.uniform(0.2, 0.8), 3),
                    )
                )
            t += dur
        return FaultPlan(faults)


class _BoundFS:
    """Per-component fs-hook adapter: remembers which component key the
    hook belongs to (the controller scopes fs faults by key)."""

    __slots__ = ("_ctl", "_key")

    def __init__(self, ctl: "FaultController", key):
        self._ctl = ctl
        self._key = key

    def on_fs_op(self, op: str, path: str) -> None:
        self._ctl.on_fs_op(self._key, op, path)


class RecoverySLAViolation(AssertionError):
    """The cluster failed to re-converge within the tick bound after
    the fault plan healed.  When any host in the checked cluster has a
    flight recorder/tracer, ``timeline`` carries the merged cross-host
    timeline captured at violation time (also logged) — the
    post-incident view, taken automatically (obs/, docs/OBSERVABILITY.md)."""

    timeline: str = ""


def _sla_violation(hosts, shard_id: int, msg: str) -> RecoverySLAViolation:
    """Build the violation with the merged flight-recorder/trace
    timeline auto-dumped into it (obs.attach_timeline; a dump failure
    must never mask the violation itself)."""
    exc = RecoverySLAViolation(msg)
    try:
        from .obs import attach_timeline
    except Exception:  # noqa: BLE001 — observability is best-effort
        return exc
    return attach_timeline(
        exc, hosts, shard_id=shard_id,
        label=f"recovery SLA violated for shard {shard_id}", log=_log,
    )


class RecoverySLAAborted(Exception):
    """The SLA check was cut short by ``should_abort`` (teardown) —
    no verdict, neither a pass nor a violation."""


class RecoveryStats:
    """Process-wide recovery aggregator, one bucket per ``fault_class``
    (the label :func:`assert_recovery_sla` stamps on each check).

    Every SLA check that reaches a verdict records its wall recovery
    time and its margin against the tick budget here, so consumers that
    need "recovery per disturbance class" — the scenario orchestrator's
    ``DayReport`` dip table (docs/SCENARIO.md) foremost — read ONE
    source instead of wrapping every recovery in an ad-hoc timer.
    Aborted checks (:class:`RecoverySLAAborted`) record nothing: an
    abort has no verdict.  ``reset()`` starts a fresh measurement epoch
    (the runner calls it at day start); snapshot() is cheap enough for
    per-phase ledger sampling."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: Dict[str, List[float]] = {}  # guarded-by: _lock
        self._violations: Dict[str, int] = {}  # guarded-by: _lock
        self._min_margin: Dict[str, float] = {}  # guarded-by: _lock

    def record(
        self, fault_class: str, seconds: float, budget: float, ok: bool
    ) -> None:
        cls = fault_class or "unclassified"
        margin = budget - seconds
        with self._lock:
            self._samples.setdefault(cls, []).append(float(seconds))
            if not ok:
                self._violations[cls] = self._violations.get(cls, 0) + 1
            cur = self._min_margin.get(cls)
            if cur is None or margin < cur:
                self._min_margin[cls] = margin

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._violations.clear()
            self._min_margin.clear()

    def snapshot(self) -> Dict[str, dict]:
        """``{fault_class: {count, worst_s, p99_s, violations,
        min_margin_s}}`` over everything recorded since the last
        reset()."""
        with self._lock:
            samples = {k: list(v) for k, v in self._samples.items()}
            violations = dict(self._violations)
            margins = dict(self._min_margin)
        out: Dict[str, dict] = {}
        for cls, xs in samples.items():
            s = sorted(xs)
            p99_i = max(0, math.ceil(len(s) * 0.99) - 1)
            out[cls] = {
                "count": len(s),
                "worst_s": round(s[-1], 4),
                "p99_s": round(s[p99_i], 4),
                "violations": violations.get(cls, 0),
                "min_margin_s": round(margins.get(cls, 0.0), 4),
            }
        return out


#: the process-wide aggregator every assert_recovery_sla records into
RECOVERY_STATS = RecoveryStats()


def assert_recovery_sla(
    nhs: Dict,
    shard_id: int = 1,
    sla_ticks: int = 5000,
    cmd: Optional[bytes] = None,
    rtt_ms: Optional[int] = None,
    per_try_timeout: float = 1.0,
    should_abort: Optional[Callable[[], bool]] = None,
    fault_class: str = "",
) -> int:
    """Recovery-SLA invariant: after faults heal, the cluster must
    re-establish FULL leader coverage (every NodeHost knows the same
    leader) and — when ``cmd`` is given — resume commit progress, all
    within ``sla_ticks`` logical ticks (converted to wall time via the
    hosts' rtt).  ``per_try_timeout`` must exceed the cluster's commit
    latency (at launch-generation scale a 1s try can never witness its
    own commit — derive it from an observed p99, e.g.
    ``LatencyBudget.per_try_timeout()``).  ``should_abort`` is polled
    between waits/tries (a caller's stop flag — the nemesis thread must
    not sit in a minutes-long SLA wait while teardown joins it); when
    it fires, :class:`RecoverySLAAborted` is raised — an aborted check
    has NO verdict.  ``fault_class`` labels the disturbance being
    recovered from ("leader_kill", "rolling_restart", ...); every
    verdict — pass or violation — lands in :data:`RECOVERY_STATS`
    under that label with its wall recovery time and budget margin.
    Returns the leader id.  Raises :class:`RecoverySLAViolation`
    otherwise."""
    hosts = list(nhs.values())
    if not hosts:
        raise ValueError("no nodehosts")
    if rtt_ms is None:
        rtt_ms = max(nh.config.rtt_millisecond for nh in hosts)
    budget = sla_ticks * rtt_ms / 1000.0
    t_start = time.monotonic()
    deadline = t_start + budget
    leader = None
    while time.monotonic() < deadline:
        if should_abort is not None and should_abort():
            raise RecoverySLAAborted(f"shard {shard_id}: caller stopping")
        seen = set()
        for nh in hosts:
            try:
                lid, ok = nh.get_leader_id(shard_id)
            except Exception:  # noqa: BLE001 — shard mid-restart etc.
                # the whole point of the SLA is that a just-healed
                # cluster may still be re-adding shards: not-found /
                # closed hosts count as "not converged yet", not a crash
                ok = False
            if not ok:
                break
            seen.add(lid)
        else:
            if len(seen) == 1:
                leader = seen.pop()
                break
        time.sleep(0.02)
    if leader is None:
        RECOVERY_STATS.record(
            fault_class, time.monotonic() - t_start, budget, ok=False
        )
        raise _sla_violation(
            hosts, shard_id,
            f"no full leader coverage for shard {shard_id} within "
            f"{sla_ticks} ticks ({budget:.1f}s)",
        )
    if cmd is not None:
        from .client import propose_with_retry

        nh = hosts[0]
        # sliced so should_abort is polled between tries: one slice is
        # a couple of tries, and an in-flight sync_propose blocks at
        # most per_try_timeout — the bound on abort latency
        while True:
            if should_abort is not None and should_abort():
                raise RecoverySLAAborted(f"shard {shard_id}: caller stopping")
            slice_end = min(
                deadline,
                time.monotonic() + max(2.0 * per_try_timeout, 2.0),
            )
            try:
                propose_with_retry(
                    nh,
                    nh.get_noop_session(shard_id),
                    cmd,
                    deadline=slice_end,
                    per_try_timeout=per_try_timeout,
                )
                break
            except Exception as e:  # noqa: BLE001 — retry until the SLA
                # deadline; the verdict at the deadline is the same
                # violation whether the error was transient or terminal
                if time.monotonic() >= deadline:
                    RECOVERY_STATS.record(
                        fault_class, time.monotonic() - t_start, budget,
                        ok=False,
                    )
                    raise _sla_violation(
                        hosts, shard_id,
                        f"no commit progress on shard {shard_id} within "
                        f"{sla_ticks} ticks ({budget:.1f}s): {e!r}",
                    ) from e
    RECOVERY_STATS.record(
        fault_class, time.monotonic() - t_start, budget, ok=True
    )
    return leader


class FaultController:
    """Seeded nemesis: owns the fault plan, the hook plane and the
    deterministic event log.

    Imperative use (most ported chaos tests)::

        ctl = FaultController(seed=7)
        ctl.install_transport(nh.transport)
        f = ctl.activate(Fault("partition", targets=("nh-1",)))
        ... shake ...
        ctl.deactivate(f)            # or ctl.heal_wire() / ctl.heal_all()

    Declarative use (the soak / acceptance scenarios)::

        ctl = FaultController(seed=7, plan=FaultPlan([...]))
        ctl.start(); ctl.wait()
        assert_recovery_sla(nhs, cmd=...)
    """

    def __init__(self, seed: int = 0, plan: Optional[FaultPlan] = None):
        self.seed = seed
        self.plan = plan or FaultPlan()
        self._lock = threading.RLock()
        self._active: List[Fault] = []
        self._lane_rngs: Dict[Tuple, Random] = {}
        # (source, target) -> payload held back by an active reorder
        self._held: Dict[Tuple[str, str], object] = {}
        self.event_log: List[Tuple] = []
        self._seq = 0
        self.stats: Dict[str, int] = {}
        self._crash_fn: Optional[Callable] = None
        self._restart_fn: Optional[Callable] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # -- churn plane (install_churn) --------------------------------
        self._churn_hosts = None  # dict or callable -> {key: NodeHost}
        self._churn_shards: Tuple = ()
        self._churn_balancer = None
        self._churn_kill_fn: Optional[Callable] = None
        self._churn_restart_fn: Optional[Callable] = None
        self._churn_sla_ticks = 0
        self._churn_sla_cmd = None
        self._churn_sla_per_try = 1.0
        self._churn_member_seq = 0
        self._churn_state: Dict[int, Tuple] = {}  # id(fault) -> victim
        # runtime-sampled victims/outcomes (NOT part of the byte-
        # identical event_log contract — leaders are schedule-dependent;
        # churn_log has its OWN counter so notes never perturb the
        # event_log sequence numbers)
        self.churn_log: List[Tuple] = []
        self._churn_seq = 0
        # per-event recovery-SLA misses (re-election bound / commit
        # continuity); tests assert this stays empty
        self.churn_violations: List[str] = []
        self.metrics = None  # set by install_churn (or directly)
        # flight recorders tapped into the fault plane (obs/): every
        # activate/heal and churn action lands in the recorders' rings
        # so a post-incident dump shows WHAT the nemesis did between
        # the cluster's own state transitions
        self._recorders: List = []

    # ------------------------------------------------------------------
    # installation
    # ------------------------------------------------------------------
    def install_transport(self, transport) -> None:
        """Install on a ``Transport`` wrapper (propagates to its raw
        ITransport) or directly on a raw transport."""
        setter = getattr(transport, "set_fault_injector", None)
        if setter is not None:
            setter(self)
        else:
            transport.fault_injector = self

    def install_vfs(self, key, fs) -> None:
        fs.fault_injector = _BoundFS(self, key)

    def install_logdb(self, key, logdb) -> None:
        logdb.fault_injector = _BoundFS(self, key)

    def install_engine(self, engine) -> None:
        engine.fault_injector = self

    def install_nodehost(self, key, nh) -> None:
        """Wire one NodeHost's transport + logdb in one call (plus its
        flight recorder, when NodeHostConfig.enable_flight_recorder is
        on — nemesis actions belong on the same timeline as the state
        transitions they cause)."""
        self.install_transport(nh.transport)
        self.install_logdb(key, nh.logdb)
        rec = getattr(nh, "recorder", None)
        if rec is not None:
            self.install_recorder(rec)

    def install_recorder(self, recorder) -> None:
        """Tap the fault plane into an obs.FlightRecorder: fault
        activations/heals and churn actions are recorded alongside the
        cluster's own state transitions."""
        with self._lock:
            if recorder not in self._recorders:
                self._recorders.append(recorder)

    def install_balancer(self, balancer) -> None:
        """Install on a balance-plane Balancer (its executor consults
        :meth:`on_balance_step` before every move step)."""
        balancer.fault_injector = self

    def set_crash_handlers(
        self, crash_fn: Callable, restart_fn: Callable
    ) -> None:
        """``crash_fn(key)`` / ``restart_fn(key)`` from the harness;
        consumed by ``crash`` faults."""
        self._crash_fn = crash_fn
        self._restart_fn = restart_fn

    def install_churn(
        self,
        hosts,
        *,
        shards: Sequence[int] = (1,),
        balancer=None,
        kill_fn: Optional[Callable] = None,
        restart_fn: Optional[Callable] = None,
        sla_ticks: int = 0,
        sla_cmd=None,
        sla_per_try: float = 1.0,
        metrics=None,
    ) -> None:
        """Arm the churn plane (kinds in :data:`CHURN_KINDS`).

        ``hosts`` is a ``{host_key: NodeHost}`` dict or a zero-arg
        callable returning one (re-read per event — churn kills hosts).
        ``kill_fn(host_key, shard_id)`` / ``restart_fn(host_key,
        shard_id)`` override the kill granularity; by default the
        PROCESS-plane crash handlers are used (whole-host kill).  With
        ``sla_ticks`` > 0 every churn event is followed by a
        per-event recovery-SLA check — full re-election within the tick
        bound plus (when ``sla_cmd`` bytes or a zero-arg callable
        producing them is given) commit continuity — and misses are
        appended to :attr:`churn_violations`.  ``metrics`` (a
        MetricsRegistry) receives ``churn_events_total{kind=...}`` and
        ``churn_sla_violations_total`` counters."""
        self._churn_hosts = hosts
        self._churn_shards = tuple(shards)
        self._churn_balancer = balancer
        self._churn_kill_fn = kill_fn
        self._churn_restart_fn = restart_fn
        self._churn_sla_ticks = sla_ticks
        self._churn_sla_cmd = sla_cmd
        self._churn_sla_per_try = sla_per_try
        if metrics is not None:
            self.metrics = metrics

    # ------------------------------------------------------------------
    # imperative fault control
    # ------------------------------------------------------------------
    def activate(self, fault: Fault) -> Fault:
        with self._lock:
            self._active.append(fault)
            self._record("activate", fault)
        if fault.kind == "crash" and self._crash_fn is not None:
            for t in fault.targets:
                self._crash_fn(t)
        elif fault.kind in CHURN_KINDS:
            self._churn_apply(fault)
        return fault

    def deactivate(self, fault: Fault) -> None:
        with self._lock:
            try:
                self._active.remove(fault)
            except ValueError:
                return
            self._record("heal", fault)
            if fault.kind == "reorder" and not any(
                f.kind == "reorder" for f in self._active
            ):
                # DISCARD held payloads once no reorder window remains
                # (there is no delivery path from here).  Message-batch
                # loss is raft-safe; a held snapshot chunk already
                # failed its send loudly (see the transports' chunk
                # lanes), so nothing waits on these.
                self._held.clear()
        if fault.kind == "crash" and self._restart_fn is not None:
            for t in fault.targets:
                self._restart_fn(t)
        elif fault.kind in CHURN_KINDS:
            self._churn_heal(fault)

    def set_partition(self, side: Sequence[str], both_ways: bool = True) -> Fault:
        """Replace any current partition with a new one (test helper)."""
        with self._lock:
            for f in [f for f in self._active if f.kind == "partition"]:
                self._active.remove(f)
                self._record("heal", f)
        return self.activate(
            # sorted: callers pass sets, and describe() is the canonical
            # byte-form of the schedule — hash-randomized set order would
            # break cross-process event-log comparison
            Fault("partition", targets=tuple(sorted(side)), both_ways=both_ways)
        )

    def heal_wire(self) -> None:
        self._heal_kinds(WIRE_KINDS)

    def heal_all(self) -> None:
        self._heal_kinds(ALL_KINDS)

    def _heal_kinds(self, kinds, restart: bool = True) -> None:
        crashed = []
        churned = []
        with self._lock:
            for f in [f for f in self._active if f.kind in kinds]:
                self._active.remove(f)
                self._record("heal", f)
                if f.kind == "crash":
                    crashed.append(f)
                elif f.kind in CHURN_KINDS:
                    churned.append(f)
            self._held.clear()
        if restart and self._restart_fn is not None:
            for f in crashed:
                for t in f.targets:
                    self._restart_fn(t)
        for f in churned:
            if restart:
                self._churn_heal(f)
            else:
                # teardown path: abandon victim state without restarting
                # onto a cluster being closed (mirrors crash semantics)
                self._churn_state.pop(id(f), None)

    def active_faults(self) -> List[Fault]:
        with self._lock:
            return list(self._active)

    def has_active(self, kind: str) -> bool:
        """Cheap gate for hot paths (the engines check it once per
        launch before paying for per-row hook calls)."""
        with self._lock:
            return any(f.kind == kind for f in self._active)

    def _record(self, action: str, fault: Fault) -> None:
        # plan-step-indexed, wall-clock-free: the determinism contract
        self.event_log.append((self._seq, action, fault.describe()))
        self._seq += 1
        self._rec_fr(self._fault_shard(fault), f"fault:{action}",
                     fault.describe())

    @staticmethod
    def _fault_shard(fault: Fault) -> int:
        """Flight-recorder lane for a fault: churn faults target shard
        ids (record in that shard's ring); wire/fs/process faults
        target host/component keys (global lane 0)."""
        if fault.kind in CHURN_KINDS and fault.targets:
            t = fault.targets[0]
            if isinstance(t, int):
                return t
        return 0

    def _rec_fr(self, shard_id: int, kind: str, detail: str) -> None:
        """Fan a nemesis event out to the tapped flight recorders —
        observability must never break the fault plane."""
        for r in self._recorders:
            try:
                r.record(shard_id, kind, detail)
            except Exception:  # noqa: BLE001
                _log.exception("flight recorder tap raised")

    def _count(self, key: str) -> None:
        with self._lock:
            self.stats[key] = self.stats.get(key, 0) + 1

    def _draw(self, kind: str, source, target, ptype: str = "") -> float:
        """One deterministic per-lane uniform draw.  Lanes are keyed by
        payload type too (a MessageBatch sender thread and a snapshot
        stream-job thread share (source, target) but must not interleave
        draws from one RNG), and the draw happens under the controller
        lock so concurrent lanes can't corrupt each other's sequences."""
        key = (kind, source, target, ptype)
        with self._lock:
            rng = self._lane_rngs.get(key)
            if rng is None:
                seed = zlib.crc32(
                    f"{self.seed}:{kind}:{source}:{target}:{ptype}".encode()
                )
                rng = self._lane_rngs.setdefault(key, Random(seed))
            return rng.random()

    # ------------------------------------------------------------------
    # plan execution (nemesis thread)
    # ------------------------------------------------------------------
    def start(self) -> "FaultController":
        if self._thread is not None:
            raise RuntimeError("nemesis already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run_plan, daemon=True, name="tpu-raft-nemesis"
        )
        self._thread.start()
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def stop(self) -> None:
        """Tear the nemesis down.  Active faults are healed WITHOUT
        firing restart handlers — stop() runs from teardown/finally
        paths where restarting a crashed node (onto a cluster being
        closed) would only add churn, and a restart failure there would
        mask the original test error (review finding).  Use heal_all()
        for a mid-run heal that should restart crashed nodes."""
        self._stop.set()
        if self._thread is not None:
            # bound the join by the nemesis thread's worst-case
            # non-abortable wait: an SLA probe slice (~2 tries), a
            # member_cycle call_with_retry (8s) or a balance-move
            # worker join (10s), plus margin — healing while the
            # nemesis still runs would race _churn_state
            self._thread.join(
                timeout=max(30.0, 2.0 * self._churn_sla_per_try + 10.0)
            )
            if self._thread.is_alive():
                _log.warning(
                    "nemesis thread did not exit before stop() healed; "
                    "teardown may race a stuck churn event"
                )
            self._thread = None
        self._heal_kinds(ALL_KINDS, restart=False)

    def run_phase(
        self, plan: FaultPlan, timeout: Optional[float] = None
    ) -> bool:
        """Execute one declarative plan to completion and return whether
        it finished (False = timeout with the nemesis thread still
        live).  The scenario orchestrator's phase hook: a production-day
        run is a SEQUENCE of plans over ONE controller, so every phase
        shares the seed, the per-lane RNGs and the event log (phase
        boundaries are visible as plan gaps; docs/SCENARIO.md).  Unlike
        :meth:`start`, the controller is reusable immediately after a
        completed phase."""
        if self._thread is not None:
            raise RuntimeError("nemesis already running a plan")
        self.plan = plan
        self.start()
        done = self.wait(timeout)
        if done:
            self._thread = None
        return done

    def _run_plan(self) -> None:
        # timeline = activations + heals merged in schedule order; ties
        # break by plan position so execution order is deterministic
        timeline: List[Tuple[float, int, str, Fault]] = []
        for i, f in enumerate(self.plan.faults):
            timeline.append((f.at, i, "activate", f))
            timeline.append((f.at + max(f.duration, 0.0), i, "heal", f))
        timeline.sort(key=lambda e: (e[0], e[1], e[2] == "heal"))
        t0 = time.monotonic()
        for when, _i, action, f in timeline:
            while not self._stop.is_set():
                lag = when - (time.monotonic() - t0)
                if lag <= 0:
                    break
                time.sleep(min(lag, 0.05))
            if self._stop.is_set():
                return
            if action == "activate":
                self.activate(f)
            else:
                self.deactivate(f)

    # ------------------------------------------------------------------
    # the hook plane
    # ------------------------------------------------------------------
    def on_wire(self, source: str, target: str, payload) -> List:
        """Filter one outbound payload (MessageBatch or Chunk).
        Returns the list of payloads to deliver now — possibly empty
        (drop/partition/held), possibly longer than one (duplicate, or
        a reorder releasing its held message)."""
        # reorder lanes are keyed by payload TYPE too: batches and
        # snapshot chunks share (source, target) but travel different
        # connections — swapping across them would hand a Chunk to the
        # message path (review finding)
        lane = (source, target, payload.__class__.__name__)
        with self._lock:
            active = list(self._active)
            held = self._held.pop(lane, None)
        # a released held payload joins BEFORE the fault loop, so an
        # active partition/drop window applies to it too — appending it
        # afterwards would let a held message cross a live partition
        # (review finding)
        out: List = [payload] if held is None else [payload, held]
        for f in active:
            if not out:
                break
            if f.kind == "partition":
                a = set(f.targets)
                cut = (
                    (source in a) != (target in a)
                    if f.both_ways
                    else (source in a and target not in a)
                )
                if cut:
                    self._count("wire_partitioned")
                    out = []
            elif f.kind in ("asym_drop", "asym_delay"):
                # directional: matched by the (source, target) PAIR —
                # this must precede the generic source filter below,
                # which would mis-read the "src->dst" targets as
                # source addresses and skip every payload
                if f.targets and f"{source}->{target}" not in f.targets:
                    continue
                if f.kind == "asym_drop":
                    if self._draw("asym_drop", source, target, lane[2]) < f.p:
                        self._count("wire_asym_dropped")
                        out = []
                elif self._draw("asym_delay", source, target, lane[2]) < f.p:
                    self._count("wire_asym_delayed")
                    time.sleep(f.delay)
            elif f.targets and source not in f.targets:
                continue
            elif f.kind == "drop":
                if self._draw("drop", source, target, lane[2]) < f.p:
                    self._count("wire_dropped")
                    out = []
            elif f.kind == "delay":
                if self._draw("delay", source, target, lane[2]) < f.p:
                    self._count("wire_delayed")
                    time.sleep(f.delay)
            elif f.kind == "duplicate":
                if self._draw("duplicate", source, target, lane[2]) < f.p:
                    self._count("wire_duplicated")
                    out = out + [out[0]]
            elif f.kind == "reorder":
                if self._draw("reorder", source, target, lane[2]) < f.p:
                    self._count("wire_reordered")
                    with self._lock:
                        # at most one held payload per lane; a second
                        # trigger releases the first (swapped)
                        if lane not in self._held:
                            self._held[lane] = out.pop(0)
            elif f.kind == "chunk_corrupt":
                out = [self._maybe_corrupt(f, source, target, p) for p in out]
        return out

    def _maybe_corrupt(self, f: Fault, source, target, payload):
        data = getattr(payload, "data", None)
        chunk_id = getattr(payload, "chunk_id", None)
        if chunk_id is None or not data:
            return payload  # not a snapshot chunk (or empty/dummy)
        if self._draw("chunk_corrupt", source, target) >= f.p:
            return payload
        import dataclasses

        pos = min(
            int(self._draw("chunk_corrupt_pos", source, target) * len(data)),
            len(data) - 1,
        )
        corrupted = data[:pos] + bytes([data[pos] ^ 0xFF]) + data[pos + 1:]
        self._count("chunks_corrupted")
        return dataclasses.replace(payload, data=corrupted)

    def on_fs_op(self, key, op: str, path: str) -> None:
        """Storage hook: raise to inject an I/O error at this exact
        durability point."""
        with self._lock:
            active = list(self._active)
        for f in active:
            if f.targets and key not in f.targets:
                continue
            if f.kind == "fsync_err" and op in _SYNC_OPS:
                if self._draw("fsync_err", key, op) < f.p:
                    self._count("fs_fsync_errors")
                    raise OSError(f"nemesis: injected fsync error ({op} {path})")
            elif f.kind == "torn_write" and op in ("write", "wal_append"):
                # on a cooperating FS (StrictMemFS) the prefix persists;
                # the WAL append path can't split a frame and treats the
                # TornWriteError as a plain injected I/O failure
                if self._draw("torn_write", key, op) < f.p:
                    self._count("fs_torn_writes")
                    raise TornWriteError(
                        keep=self._draw("torn_write_keep", key, op)
                    )
            elif f.kind == "write_err" and op in _WRITE_OPS:
                if self._draw("write_err", key, op) < f.p:
                    self._count("fs_write_errors")
                    raise OSError(f"nemesis: injected write error ({op} {path})")

    def on_snapshot_stream(self, source: str, target: str, chunk) -> None:
        """Stream-job hook, consulted per outbound snapshot chunk
        (transport.Transport._stream_once).  ``snapshot_stream_kill``
        raises — the streamer dies mid-transfer and the sender's
        bounded-retry path must resume from the receiver's cursor;
        ``snapshot_stream_stall`` sleeps ``delay`` seconds.  Kills only
        strike past chunk 0 so every killed transfer IS mid-transfer
        (a pre-first-chunk kill would test plain retry, not resume) —
        which also means a witness's DUMMY stream (exactly one chunk,
        chunk_id 0) is structurally immune to kills: it either lands
        whole or the ordinary send-failure retry applies.  Targets
        match the SENDER address, or the RECEIVER when written as
        ``dst:<addr>`` (docs/FAULTS.md, witness/dummy chaos)."""
        with self._lock:
            active = list(self._active)
        for f in active:
            if f.kind not in STREAM_KINDS:
                continue
            if (
                f.targets
                and source not in f.targets
                and (STREAM_DST_PREFIX + str(target)) not in f.targets
            ):
                continue
            if f.kind == "snapshot_stream_stall":
                if self._draw("snapshot_stream_stall", source, target) < f.p:
                    self._count("stream_stalled")
                    time.sleep(f.delay)
            elif chunk.chunk_id > 0:
                if self._draw("snapshot_stream_kill", source, target) < f.p:
                    self._count("stream_kills")
                    raise ConnectionError(
                        "nemesis: snapshot streamer killed mid-transfer"
                    )

    def on_balance_step(self, shard_id: int, step: str) -> bool:
        """Balance hook, consulted by the move executor before each step
        of the add -> catchup -> transfer -> remove sequence.  True tells
        the executor to abort the move (it must then roll back); an
        active ``balance_stall`` window sleeps here instead, stretching
        the step so other planes can strike mid-move."""
        with self._lock:
            active = list(self._active)
        for f in active:
            if f.kind not in BALANCE_KINDS:
                continue
            if f.targets and shard_id not in f.targets:
                continue
            if f.kind == "balance_stall":
                if self._draw("balance_stall", shard_id, step) < f.p:
                    self._count("balance_stalled")
                    time.sleep(f.delay)
            elif self._draw("balance_abort", shard_id, step) < f.p:
                self._count("balance_aborted")
                return True
        return False

    def on_engine_step(self, shard_id: int, replica_id: int) -> bool:
        """Engine hook: True forces the kernel-escalation recovery path
        for this row this launch."""
        with self._lock:
            active = list(self._active)
        for f in active:
            if f.kind != "escalate":
                continue
            if f.targets and shard_id not in f.targets:
                continue
            if self._draw("escalate", shard_id, replica_id) < f.p:
                self._count("engine_escalations")
                return True
        return False

    # ------------------------------------------------------------------
    # the churn plane (install_churn)
    # ------------------------------------------------------------------
    # churn_log actions that INITIATE an executed event — skips, errors,
    # unresolved/leak notes and heal halves (restart, member_remove)
    # must not inflate churn_events_total: one scheduled fault is one
    # event, and a run where every event skipped must not look like one
    # that churned
    _CHURN_EXECUTED = frozenset(
        ("kill", "transfer", "member_add", "balance")
    )

    def _churn_note(self, fault: Fault, action: str, detail: str) -> None:
        with self._lock:
            self.churn_log.append(
                (self._churn_seq, fault.kind, action, detail)
            )
            self._churn_seq += 1
        # the victim-resolved action (e.g. WHICH host a leader_kill hit)
        # belongs on the shard's flight-recorder timeline — this is the
        # "injected leader-kill" marker the post-incident dump shows
        # between the last pre-kill apply and the re-election
        self._rec_fr(self._fault_shard(fault),
                     f"churn:{fault.kind}:{action}", detail)
        if self.metrics is not None and action in self._CHURN_EXECUTED:
            self.metrics.counter(
                "churn_events_total", {"kind": fault.kind}
            ).add()

    def _churn_live_hosts(self) -> Dict:
        h = self._churn_hosts
        if h is None:
            return {}
        d = h() if callable(h) else h
        return {
            k: nh for k, nh in d.items() if not getattr(nh, "_closed", False)
        }

    def _churn_pick_shard(self, fault: Fault) -> Optional[int]:
        if fault.targets:
            return fault.targets[0]
        if not self._churn_shards:
            return None
        i = int(
            self._draw("churn_shard", fault.kind, fault.at)
            * len(self._churn_shards)
        ) % len(self._churn_shards)
        return self._churn_shards[i]

    def _find_leader(self, shard_id: int):
        """(host_key, nodehost, leader_replica_id) of the shard's
        current leader, or None while leaderless/mid-restart."""
        hosts = self._churn_live_hosts()
        lid = 0
        for nh in hosts.values():
            try:
                l, ok = nh.get_leader_id(shard_id)
            except Exception:  # noqa: BLE001 — host may not hold the shard
                continue
            if ok and l:
                lid = l
                break
        if not lid:
            return None
        for key, nh in hosts.items():
            node = nh._nodes.get(shard_id)
            if node is not None and node.replica_id == lid:
                return key, nh, lid
        return None

    def _churn_apply(self, fault: Fault) -> None:
        if self._churn_hosts is None:
            self._churn_note(fault, "skip", "churn plane not installed")
            return
        try:
            if fault.kind == "leader_kill":
                self._churn_leader_kill(fault)
            elif fault.kind == "leader_transfer":
                self._churn_leader_transfer(fault)
            elif fault.kind == "member_cycle":
                self._churn_member_add(fault)
            elif fault.kind == "balance_move":
                self._churn_balance_move(fault)
        except Exception as e:  # noqa: BLE001 — the schedule must go on
            _log.warning("churn %s failed: %r", fault.kind, e)
            self._churn_note(fault, "error", repr(e))

    def _churn_heal(self, fault: Fault) -> None:
        try:
            if fault.kind == "leader_kill":
                v = self._churn_state.pop(id(fault), None)
                if v is not None:
                    shard_id, key = v
                    fn = self._churn_restart_fn
                    if fn is not None:
                        fn(key, shard_id)
                    elif self._restart_fn is not None:
                        self._restart_fn(key)
                    self._churn_note(
                        fault, "restart", f"shard={shard_id} host={key}"
                    )
                    self._churn_sla(shard_id, fault.kind)
            elif fault.kind == "member_cycle":
                v = self._churn_state.pop(id(fault), None)
                if v is not None:
                    self._churn_member_remove(fault, *v)
            elif fault.kind == "balance_move":
                t = self._churn_state.pop(id(fault), None)
                if t is not None:
                    t.join(timeout=10.0)
        except Exception as e:  # noqa: BLE001
            _log.warning("churn heal %s failed: %r", fault.kind, e)
            self._churn_note(fault, "error", repr(e))

    def _churn_leader_kill(self, fault: Fault) -> None:
        shard_id = self._churn_pick_shard(fault)
        found = shard_id and self._find_leader(shard_id)
        if not found:
            self._churn_note(
                fault, "skip", f"no leader found (shard={shard_id})"
            )
            return
        key, _nh, lid = found
        self._churn_state[id(fault)] = (shard_id, key)
        fn = self._churn_kill_fn
        if fn is not None:
            fn(key, shard_id)
        elif self._crash_fn is not None:
            self._crash_fn(key)
        else:
            self._churn_state.pop(id(fault), None)
            self._churn_note(fault, "skip", "no kill handler installed")
            return
        self._count("churn_leader_kills")
        self._churn_note(
            fault, "kill", f"shard={shard_id} host={key} leader={lid}"
        )

    def _churn_leader_transfer(self, fault: Fault) -> None:
        shard_id = self._churn_pick_shard(fault)
        found = shard_id and self._find_leader(shard_id)
        if not found:
            self._churn_note(
                fault, "skip", f"no leader found (shard={shard_id})"
            )
            return
        key, nh, lid = found
        node = nh._nodes.get(shard_id)
        if node is None:
            self._churn_note(fault, "skip", "leader node vanished")
            return
        voters = sorted(
            r for r in node.get_membership().addresses if r != lid
        )
        if not voters:
            self._churn_note(fault, "skip", "no transfer candidate")
            return
        target = voters[
            int(self._draw("churn_transfer", shard_id, lid) * len(voters))
            % len(voters)
        ]
        nh.request_leader_transfer(shard_id, target)
        self._count("churn_leader_transfers")
        self._churn_note(
            fault, "transfer", f"shard={shard_id} {lid} -> {target}"
        )
        self._churn_sla(shard_id, fault.kind)

    def _churn_member_add(self, fault: Fault) -> None:
        shard_id = self._churn_pick_shard(fault)
        hosts = self._churn_live_hosts()
        if not shard_id or not hosts:
            self._churn_note(fault, "skip", "no shard/hosts")
            return
        keys = sorted(hosts, key=str)
        addr_key = keys[
            int(self._draw("churn_member", shard_id, fault.at) * len(keys))
            % len(keys)
        ]
        addr = hosts[addr_key].raft_address()
        api = self._churn_api_host(shard_id)
        if api is None:
            self._churn_note(fault, "skip", "no live host holds the shard")
            return
        with self._lock:
            self._churn_member_seq += 1
            rid = 70_000 + self._churn_member_seq
        # the throwaway rid must clear EVERY id the shard has ever seen:
        # other planes allocate max(known ids)+1 (the balance executor's
        # next_replica_id walks voters+non-votings+witnesses+removed), so
        # a fixed 70_000+seq can COLLIDE with a move-created voter once a
        # churned id lands in `removed` — the add then rejects and the
        # heal would remove a REAL member (found by the production-day
        # soak: cycle-1 member_cycle deleted the voter cycle-0's drain
        # had just placed, docs/SCENARIO.md)
        try:
            m = api.get_shard_membership(shard_id)
            known = [
                *m.addresses, *m.non_votings, *m.witnesses, *m.removed,
            ]
            rid = max(rid, max(known, default=0) + 1)
        except Exception:  # noqa: BLE001 — membership mid-change; the
            # remove-side guard still protects real members
            pass
        from .client import call_with_retry

        # record the victim BEFORE the RPC: an add whose ack times out
        # may still have committed, and the heal must try the remove
        # either way (removing a never-committed member just rejects,
        # which the remove path counts as member_leak noise — better
        # than a phantom non-voting member replicated-to forever).  The
        # ADDRESS rides along so the heal can recognize a non-voting
        # that is NOT ours (a concurrent plane winning the same rid)
        self._churn_state[id(fault)] = (shard_id, rid, addr)
        # the new member is never started: a transiently-unreachable
        # NON-VOTING add (quorum untouched) the heal removes again —
        # the membership entries themselves are the churn
        try:
            call_with_retry(
                lambda: api.sync_request_add_non_voting(
                    shard_id, rid, addr, timeout=1.0
                ),
                timeout=8.0,
            )
        except Exception as e:  # noqa: BLE001 — maybe-committed add
            self._count("churn_member_add_unresolved")
            self._churn_note(
                fault, "member_add_unresolved",
                f"shard={shard_id} rid={rid}: {e!r}",
            )
            return
        self._count("churn_member_adds")
        self._churn_note(
            fault, "member_add", f"shard={shard_id} rid={rid} addr={addr}"
        )

    def _churn_member_remove(
        self, fault: Fault, shard_id: int, rid: int,
        addr: Optional[str] = None,
    ) -> None:
        api = self._churn_api_host(shard_id)
        if api is None:
            self._count("churn_member_failures")
            self._churn_note(
                fault, "member_leak", f"shard={shard_id} rid={rid}"
            )
            return
        # the heal may only remove the NON-VOTING member this cycle
        # added: if the rid now resolves to a voter or witness — or to
        # a non-voting at a DIFFERENT address — some other plane owns
        # it (an id collision, e.g. a concurrent balance move's
        # catch-up replica winning the same max(known)+1 draw) and
        # removing it would damage the real membership; leak loudly
        # instead
        try:
            m = api.get_shard_membership(shard_id)
            stolen = (
                rid in m.addresses
                or rid in m.witnesses
                or (
                    addr is not None
                    and m.non_votings.get(rid, addr) != addr
                )
            )
            if stolen:
                self._count("churn_member_failures")
                self._churn_note(
                    fault, "member_remove_skipped",
                    f"shard={shard_id} rid={rid} is another plane's "
                    "member (id collision), not removing",
                )
                return
        except Exception:  # noqa: BLE001 — membership mid-change; the
            # remove below still rejects ids that vanished
            pass
        from .client import call_with_retry

        try:
            call_with_retry(
                lambda: api.sync_request_delete_replica(
                    shard_id, rid, timeout=1.0
                ),
                timeout=8.0,
            )
            self._count("churn_member_removes")
            self._churn_note(
                fault, "member_remove", f"shard={shard_id} rid={rid}"
            )
        except Exception as e:  # noqa: BLE001 — a leftover non-voting
            # member is harmless to quorum; count it loudly instead of
            # failing the schedule
            self._count("churn_member_failures")
            self._churn_note(
                fault, "member_leak", f"shard={shard_id} rid={rid}: {e!r}"
            )
        self._churn_sla(shard_id, fault.kind)

    def _churn_api_host(self, shard_id: int):
        """A live host holding the shard (prefer the leader's)."""
        found = self._find_leader(shard_id)
        if found:
            return found[1]
        for nh in self._churn_live_hosts().values():
            if nh._nodes.get(shard_id) is not None:
                return nh
        return None

    def _churn_balance_move(self, fault: Fault) -> None:
        b = self._churn_balancer
        if b is None:
            self._churn_note(fault, "skip", "no balancer installed")
            return

        def run():
            try:
                report = b.rebalance_once(max_moves=1)
                self._count("churn_balance_moves")
                self._churn_note(fault, "balance", repr(report))
            except Exception as e:  # noqa: BLE001 — nemesis may abort it
                self._churn_note(fault, "balance_abort", repr(e))

        t = threading.Thread(
            target=run, daemon=True, name="tpu-raft-churn-balance"
        )
        self._churn_state[id(fault)] = t
        t.start()

    def _churn_sla(self, shard_id: int, fault_class: str = "") -> None:
        """Per-event recovery-SLA assert: full re-election within the
        tick bound + commit continuity (when a probe cmd is armed).
        Runs on the nemesis thread — the next scheduled fault fires
        after the cluster has either recovered or violated.  The churn
        kind rides along as the SLA's ``fault_class``, so every churn
        recovery lands in :data:`RECOVERY_STATS` under its own label."""
        if not self._churn_sla_ticks:
            return
        hosts = {
            k: nh
            for k, nh in self._churn_live_hosts().items()
            if nh._nodes.get(shard_id) is not None
        }
        if not hosts:
            self.churn_violations.append(
                f"shard {shard_id}: no live replica after churn event"
            )
            return
        cmd = self._churn_sla_cmd
        if callable(cmd):
            cmd = cmd()
        try:
            assert_recovery_sla(
                hosts, shard_id, sla_ticks=self._churn_sla_ticks, cmd=cmd,
                per_try_timeout=self._churn_sla_per_try,
                should_abort=self._stop.is_set,
                fault_class=fault_class,
            )
            self._count("churn_sla_ok")
        except RecoverySLAAborted:
            # teardown raced the check: no verdict, and the nemesis
            # thread exits promptly instead of outliving stop()'s join
            self._count("churn_sla_aborted")
        except RecoverySLAViolation as e:
            self._count("churn_sla_violations")
            if self.metrics is not None:
                self.metrics.counter("churn_sla_violations_total").add()
            self.churn_violations.append(f"shard {shard_id}: {e}")
