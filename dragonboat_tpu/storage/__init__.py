"""Storage layer (reference: internal/logdb/, internal/tan/ [U])."""
from .logdb import InMemLogDB, LogDBLogReader

__all__ = ["InMemLogDB", "LogDBLogReader"]
