"""Filesystem abstraction with a power-loss-faithful in-memory impl.

reference: internal/vfs (pebble vfs wrapper) [U] — the reference runs
its storage tests against ``MemFS`` in *strict* mode, where nothing
survives a simulated crash unless it was explicitly fsynced (file data)
or the parent directory was fsynced (namespace operations: create,
rename, unlink).  That discipline is where WAL bugs hide; this module
reproduces it for the tan WAL and the snapshotter.

Two implementations:

* ``OSVFS`` — thin wrappers over ``os`` (production).
* ``StrictMemFS`` — in-memory with ``crash()``: every file reverts to
  its last-synced content **plus a random prefix of its unsynced tail**
  (a torn write), and every namespace change since the last
  ``sync_dir`` is rolled back.  An optional ``fault_hook`` fires before
  each data-touching operation so tests can inject I/O errors at exact
  fsync boundaries.
"""
from __future__ import annotations

import os
import random
import threading
from typing import Callable, Dict, List, Optional, Tuple


class IVFSFile:
    """Append-oriented file handle."""

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def tell(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class IVFS:
    """The minimal FS surface the storage layer needs."""

    def open_append(self, path: str) -> IVFSFile:
        raise NotImplementedError

    def read_file(self, path: str) -> bytes:
        raise NotImplementedError

    def open_read(self, path: str):
        """Seekable read handle for INCREMENTAL consumption (the
        big-state plane reads checkpoints/WALs in bounded slices;
        ``read_file`` stays for small whole-blob reads)."""
        raise NotImplementedError

    def write_file_chunks(self, path: str, chunks) -> None:
        """Create/overwrite ``path`` from an iterable of byte chunks,
        fsync the file (NOT the directory — callers own namespace
        durability via sync_dir/rename)."""
        raise NotImplementedError

    def truncate(self, path: str, size: int) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def unlink(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        raise NotImplementedError

    def sync_dir(self, path: str) -> None:
        raise NotImplementedError

    def stat_size(self, path: str) -> int:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# OS implementation
# ---------------------------------------------------------------------------
class _OSFile(IVFSFile):
    __slots__ = ("_f",)

    def __init__(self, f):
        self._f = f

    def write(self, data: bytes) -> None:
        self._f.write(data)

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()


class OSVFS(IVFS):
    def open_append(self, path: str) -> IVFSFile:
        return _OSFile(open(path, "ab"))

    def read_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def open_read(self, path: str):
        return open(path, "rb")

    def write_file_chunks(self, path: str, chunks) -> None:
        with open(path, "wb") as f:
            for c in chunks:
                f.write(c)
            f.flush()
            os.fsync(f.fileno())

    def truncate(self, path: str, size: int) -> None:
        with open(path, "r+b") as f:
            f.truncate(size)
            f.flush()
            os.fsync(f.fileno())

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def unlink(self, path: str) -> None:
        os.unlink(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def sync_dir(self, path: str) -> None:
        dfd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def stat_size(self, path: str) -> int:
        return os.stat(path).st_size


DEFAULT = OSVFS()


# ---------------------------------------------------------------------------
# strict in-memory implementation
# ---------------------------------------------------------------------------
class _MemNode:
    """One file: synced prefix + unsynced pending tail."""

    __slots__ = ("synced", "pending")

    def __init__(self, synced: bytes = b"", pending: bytes = b""):
        self.synced = synced
        self.pending = pending

    @property
    def data(self) -> bytes:
        return self.synced + self.pending


class _MemFile(IVFSFile):
    def __init__(self, fs: "StrictMemFS", path: str):
        self._fs = fs
        self._path = path
        self._closed = False

    def write(self, data: bytes) -> None:
        try:
            self._fs._hook("write", self._path)
        except Exception as e:
            # nemesis torn write: persist the prefix the fault allows,
            # then fail — replay code must cope with the partial tail
            keep = getattr(e, "keep", None)
            if keep is not None and data:
                with self._fs._lock:
                    self._fs._node(self._path).pending += data[
                        : int(len(data) * float(keep))
                    ]
            raise
        with self._fs._lock:
            self._fs._node(self._path).pending += data

    def sync(self) -> None:
        self._fs._hook("sync", self._path)
        with self._fs._lock:
            n = self._fs._node(self._path)
            n.synced, n.pending = n.synced + n.pending, b""

    def tell(self) -> int:
        with self._fs._lock:
            return len(self._fs._node(self._path).data)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.sync()


class StrictMemFS(IVFS):
    """Strict-durability in-memory FS for crash tests.

    Namespace model: each directory tracks its *synced* entry map and
    its *current* entry map.  create/rename/unlink mutate the current
    map only; ``sync_dir`` commits it.  ``crash(rng)`` rolls every
    directory back to its synced map and every file back to its synced
    bytes plus a RANDOM PREFIX of the pending tail (torn final write).
    """

    def __init__(self):
        self._lock = threading.RLock()
        # path -> _MemNode for every file that exists in the CURRENT view
        self._files: Dict[str, _MemNode] = {}
        # dir -> {name: node} synced snapshot of the namespace
        self._synced_dirs: Dict[str, Dict[str, _MemNode]] = {}
        self._dirs: set = set()
        self.fault_hook: Optional[Callable[[str, str], None]] = None
        # the unified fault plane (faults.FaultController via a bound
        # adapter); fault_hook stays for bespoke test callbacks
        self.fault_injector = None
        self.crashes = 0

    # -- internals -------------------------------------------------------
    def _hook(self, op: str, path: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(op, path)
        if self.fault_injector is not None:
            self.fault_injector.on_fs_op(op, path)

    def _node(self, path: str) -> _MemNode:
        n = self._files.get(path)
        if n is None:
            raise FileNotFoundError(path)
        return n

    def _dir_of(self, path: str) -> str:
        return os.path.dirname(path)

    def _check_dir(self, d: str) -> None:
        if d not in self._dirs:
            raise FileNotFoundError(f"no such directory: {d}")

    # -- IVFS ------------------------------------------------------------
    def open_append(self, path: str) -> IVFSFile:
        with self._lock:
            self._check_dir(self._dir_of(path))
            if path not in self._files:
                self._hook("create", path)
                self._files[path] = _MemNode()
            return _MemFile(self, path)

    def read_file(self, path: str) -> bytes:
        with self._lock:
            return self._node(path).data

    def open_read(self, path: str):
        import io

        with self._lock:
            return io.BytesIO(self._node(path).data)

    def write_file_chunks(self, path: str, chunks) -> None:
        with self._lock:
            self._check_dir(self._dir_of(path))
            self._hook("create", path)
            node = _MemNode()
            self._files[path] = node
        for c in chunks:
            self._hook("write", path)
            with self._lock:
                node.pending += bytes(c)
        self._hook("sync", path)
        with self._lock:
            node.synced, node.pending = node.synced + node.pending, b""

    def truncate(self, path: str, size: int) -> None:
        self._hook("truncate", path)
        with self._lock:
            n = self._node(path)
            # a synced truncate is durable (used for torn-tail repair)
            n.synced, n.pending = n.data[:size], b""

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._files or path in self._dirs

    def listdir(self, path: str) -> List[str]:
        with self._lock:
            self._check_dir(path)
            pre = path.rstrip("/") + "/"
            names = set()
            for p in self._files:
                if p.startswith(pre) and "/" not in p[len(pre):]:
                    names.add(p[len(pre):])
            for d in self._dirs:
                if d.startswith(pre) and "/" not in d[len(pre):]:
                    names.add(d[len(pre):])
            return sorted(names)

    def makedirs(self, path: str) -> None:
        with self._lock:
            p = path.rstrip("/")
            parts = p.split("/")
            for i in range(1, len(parts) + 1):
                d = "/".join(parts[:i])
                if d and d not in self._dirs:
                    self._dirs.add(d)
                    self._synced_dirs.setdefault(d, {})
            # creating directories is treated as durable (mkdir+parent
            # sync happens once at startup; not the interesting case)

    def unlink(self, path: str) -> None:
        self._hook("unlink", path)
        with self._lock:
            self._node(path)
            del self._files[path]

    def rename(self, src: str, dst: str) -> None:
        self._hook("rename", src)
        with self._lock:
            n = self._node(src)
            del self._files[src]
            self._files[dst] = n

    def sync_dir(self, path: str) -> None:
        self._hook("sync_dir", path)
        with self._lock:
            self._check_dir(path)
            pre = path.rstrip("/") + "/"
            snap = {}
            for p, n in self._files.items():
                if p.startswith(pre) and "/" not in p[len(pre):]:
                    snap[p[len(pre):]] = n
            self._synced_dirs[path.rstrip("/")] = snap

    def stat_size(self, path: str) -> int:
        with self._lock:
            return len(self._node(path).data)

    # -- crash simulation ------------------------------------------------
    def crash(self, rng: Optional[random.Random] = None) -> None:
        """Simulated power loss: unsynced data and namespace ops vanish.

        Every file keeps its synced bytes plus a random prefix of its
        pending tail (the torn write the WAL replay must cope with).
        Every directory reverts to its last-synced entry map, EXCEPT
        that a file created since the dir sync MAY survive (metadata
        journaling on real filesystems makes both outcomes possible) —
        rng decides.
        """
        rng = rng or random.Random()
        with self._lock:
            self.crashes += 1
            # tear file tails
            for n in set(self._files.values()) | {
                x for d in self._synced_dirs.values() for x in d.values()
            }:
                if n.pending:
                    keep = rng.randrange(0, len(n.pending) + 1)
                    n.synced += n.pending[:keep]
                n.pending = b""
            # roll namespaces back
            new_files: Dict[str, _MemNode] = {}
            claimed = set()
            for d, snap in self._synced_dirs.items():
                for name, node in snap.items():
                    new_files[f"{d}/{name}"] = node
                    claimed.add(id(node))
            # unsynced creates: each may survive (journaled metadata)
            for p, n in self._files.items():
                if p not in new_files and id(n) not in claimed:
                    if rng.random() < 0.5:
                        new_files[p] = n
            self._files = new_files
            # the post-crash view is what's durable now
            for d in self._synced_dirs:
                self.sync_dir(d)
