"""Shared crc-framed journal segment scanner.

Both durable backends (the tan WAL and the KV store's journal) append
``<kind u8 | length u32 | crc u32 | body>`` records to numbered segment
files and replay them at open with the SAME crash rules:

  * a torn header/body at the tail of the LAST segment is the crash
    point — truncate it off durably and stop (leaving it would make the
    next open treat this segment as non-last and refuse);
  * a bad crc is accepted as a tear only when it is the FINAL record of
    the last segment; anywhere else it is corruption;
  * any structural error inside a record body is corruption.

This is subtle crash-recovery logic; keeping one copy means a fix
reaches every backend (extracted after the power-loss fuzz shook out
backend-specific copies).
"""
from __future__ import annotations

import struct
import zlib
from typing import Callable

REC_HEADER = struct.Struct("<BII")  # kind, length, crc


class CorruptJournalError(Exception):
    """Mid-journal corruption (not a clean torn tail)."""


def frame_record(kind: int, body: bytes) -> bytes:
    return REC_HEADER.pack(kind, len(body), zlib.crc32(body)) + body


def scan_segment(
    fs,
    path: str,
    directory: str,
    torn_ok: bool,
    apply: Callable[[int, bytes], None],
    error_cls=CorruptJournalError,
) -> None:
    """Replay one segment through ``apply(kind, body)``; repairs a torn
    tail (truncate + dir sync) when ``torn_ok``."""
    data = fs.read_file(path)
    pos, n = 0, len(data)
    while pos < n:
        if pos + REC_HEADER.size > n:
            if torn_ok:
                return _truncate_tail(fs, path, directory, pos)
            raise error_cls(f"{path}: torn header at {pos}")
        kind, length, crc = REC_HEADER.unpack_from(data, pos)
        body_at = pos + REC_HEADER.size
        if body_at + length > n:
            if torn_ok:
                return _truncate_tail(fs, path, directory, pos)
            raise error_cls(f"{path}: torn body at {pos}")
        body = data[body_at : body_at + length]
        if zlib.crc32(body) != crc:
            if torn_ok and body_at + length == n:
                return _truncate_tail(fs, path, directory, pos)
            raise error_cls(f"{path}: bad crc at {pos}")
        try:
            apply(kind, body)
        except error_cls:
            raise
        except Exception as e:  # noqa: BLE001 - any decode failure
            raise error_cls(f"{path}: bad record at {pos}: {e}")
        pos = body_at + length


def _truncate_tail(fs, path: str, directory: str, pos: int) -> None:
    """Cut torn bytes off a crash tail, durably."""
    fs.truncate(path, pos)
    fs.sync_dir(directory)
