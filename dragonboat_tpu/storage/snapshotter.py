"""Snapshot payload storage + the node<->rsm<->logdb snapshot bridge.

reference: snapshotter.go + internal/fileutil atomic dir finalize [U].

Two backends:
  * ``InMemSnapshotStorage`` — per-NodeHost in-memory store (tests); NOT
    shared between hosts — snapshots cross hosts only via the chunk lane.
  * ``FileSnapshotStorage`` — atomic temp-file + fsync + rename layout,
    the NodeHost default (reference: fileutil.CreateFlagFile / SyncDir [U]).
"""
from __future__ import annotations

import os
import threading
import zlib
from typing import Dict

def _checksum(data: bytes) -> bytes:
    return zlib.crc32(data).to_bytes(4, "little")


class InMemSnapshotStorage:
    """Per-NodeHost in-memory store; keys are synthetic 'paths' so
    pb.Snapshot.filepath stays meaningful.  Deliberately NOT shared between
    hosts: snapshots cross hosts only via the transport chunk lane, exactly
    as in the reference."""

    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, bytes] = {}

    def save(
        self,
        shard_id: int,
        replica_id: int,
        index: int,
        payload: bytes,
        suffix: str = "",
    ) -> str:
        path = f"mem://snapshot-{shard_id}-{replica_id}-{index:020d}"
        if suffix:
            path += f"-{suffix}"
        with self._lock:
            self._store[path] = payload
        return path

    def load(self, filepath: str) -> bytes:
        with self._lock:
            data = self._store.get(filepath)
        if data is None:
            raise FileNotFoundError(filepath)
        return data

    def remove(self, filepath: str) -> None:
        with self._lock:
            self._store.pop(filepath, None)



class FileSnapshotStorage:
    """Durable snapshot files with atomic finalize.

    Layout: <root>/snapshot-<shard>-<replica>-<index>/snapshot.bin
    written to a .generating temp dir, fsynced, then renamed — the rename
    is the commit point (reference: internal/fileutil [U]).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _dir(
        self, shard_id: int, replica_id: int, index: int, suffix: str = ""
    ) -> str:
        name = f"snapshot-{shard_id}-{replica_id}-{index:020d}"
        if suffix:
            name += f"-{suffix}"
        return os.path.join(self.root, name)

    def save(
        self,
        shard_id: int,
        replica_id: int,
        index: int,
        payload: bytes,
        suffix: str = "",
    ) -> str:
        import shutil

        final = self._dir(shard_id, replica_id, index, suffix)
        tmp = final + ".generating"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        if os.path.exists(final):
            # leftover from an earlier incarnation of this replica id (the
            # rename below cannot clobber a non-empty dir)
            shutil.rmtree(final)
        os.makedirs(tmp)
        fpath = os.path.join(tmp, "snapshot.bin")
        with open(fpath, "wb") as f:
            f.write(_checksum(payload))
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final)
        # fsync the parent so the rename itself is durable
        dfd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return os.path.join(final, "snapshot.bin")

    def load(self, filepath: str) -> bytes:
        with open(filepath, "rb") as f:
            crc = f.read(4)
            payload = f.read()
        if _checksum(payload) != crc:
            raise IOError(f"snapshot checksum mismatch: {filepath}")
        return payload

    def remove(self, filepath: str) -> None:
        import shutil

        d = os.path.dirname(filepath)
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)
