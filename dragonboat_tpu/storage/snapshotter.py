"""Snapshot payload storage + the node<->rsm<->logdb snapshot bridge.

reference: snapshotter.go + internal/fileutil atomic dir finalize [U].

Two backends:
  * ``InMemSnapshotStorage`` — per-NodeHost in-memory store (tests); NOT
    shared between hosts — snapshots cross hosts only via the chunk lane.
  * ``FileSnapshotStorage`` — atomic temp-dir + fsync + rename layout,
    the NodeHost default (reference: fileutil.CreateFlagFile / SyncDir [U]).

Payload bytes are an opaque v2 container (storage/snapshotio.py) with
its own per-section checksums; the storage layer stores them VERBATIM.
External files (ISnapshotFileCollection) are staged as siblings of
``snapshot.bin`` in the snapshot dir and referenced by relative name
from the container's file table.

Streaming surfaces:
  * ``save_stream(shard, replica, index, build, suffix)`` — ``build``
    writes the container into an open file handle with bounded memory
    and may stage external files via the passed ``copy_fn``.
  * ``open_read(filepath)`` — seekable handle for incremental reads
    (chunked sends, SnapshotReader).
  * ``lease(filepath)`` — context manager pinning the snapshot dir
    against GC while a stream job reads it.
"""
from __future__ import annotations

import contextlib
import io
import os
import shutil
import threading
from typing import Callable, Dict, List, Optional, Set

from ..pb import SnapshotFile


def _external_name(file_id: int, src: str) -> str:
    return f"external-{file_id}-{os.path.basename(src)}"


def _make_copy_fn(dst_dir: str) -> Callable:
    """The ISnapshotFileCollection staging callback: copy the SM's file
    beside the container and record it by relative name."""

    def copy_fn(file_id: int, src: str, metadata: bytes) -> SnapshotFile:
        name = _external_name(file_id, src)
        dst = os.path.join(dst_dir, name)
        shutil.copyfile(src, dst)
        return SnapshotFile(
            file_id=file_id,
            filepath=name,
            file_size=os.path.getsize(dst),
            metadata=metadata,
        )

    return copy_fn


class _LeaseMixin:
    """GC-lease bookkeeping shared by the storage backends.

    ``lease(filepath)`` pins the snapshot against ``remove`` while a
    stream job reads it; a remove during a lease is deferred to the last
    release.  Subclasses provide ``_lease_key`` (filepath -> unit of
    deletion) and ``_delete(key)``.
    """

    def _init_leases(self) -> None:
        self._lock = threading.Lock()
        self._leases: Dict[str, int] = {}
        self._pending_delete: Set[str] = set()

    @contextlib.contextmanager
    def lease(self, filepath: str):
        key = self._lease_key(filepath)
        with self._lock:
            self._leases[key] = self._leases.get(key, 0) + 1
        try:
            yield
        finally:
            delete = False
            with self._lock:
                n = self._leases[key] - 1
                if n:
                    self._leases[key] = n
                else:
                    del self._leases[key]
                    delete = key in self._pending_delete
                    self._pending_delete.discard(key)
            if delete:
                self._delete(key)

    def remove(self, filepath: str) -> None:
        key = self._lease_key(filepath)
        with self._lock:
            if self._leases.get(key, 0) > 0:
                # a stream job is reading it: defer to last lease release
                self._pending_delete.add(key)
                return
        self._delete(key)


class InMemSnapshotStorage(_LeaseMixin):
    """Per-NodeHost in-memory store; keys are synthetic 'paths' so
    pb.Snapshot.filepath stays meaningful.  Deliberately NOT shared between
    hosts: snapshots cross hosts only via the transport chunk lane, exactly
    as in the reference.  External files are materialized into a private
    real directory (user SMs read them by path)."""

    def __init__(self):
        self._init_leases()
        self._store: Dict[str, bytes] = {}
        self._ext_root: Optional[str] = None

    def _key(self, shard_id, replica_id, index, suffix="") -> str:
        path = f"mem://snapshot-{shard_id}-{replica_id}-{index:020d}"
        if suffix:
            path += f"-{suffix}"
        return path

    def _ext_dir(self, key: str) -> str:
        import tempfile

        if self._ext_root is None:
            self._ext_root = tempfile.mkdtemp(prefix="tpu-raft-memss-")
        d = os.path.join(self._ext_root, key.replace("/", "_"))
        os.makedirs(d, exist_ok=True)
        return d

    def save(self, shard_id, replica_id, index, payload, suffix="") -> str:
        path = self._key(shard_id, replica_id, index, suffix)
        with self._lock:
            self._store[path] = payload
        return path

    def save_stream(
        self,
        shard_id: int,
        replica_id: int,
        index: int,
        build: Callable,
        suffix: str = "",
        index_from_result: Optional[Callable] = None,
    ):
        path = self._key(shard_id, replica_id, index, suffix)
        ext_dir = self._ext_dir(path)
        buf = io.BytesIO()
        result = build(buf, _make_copy_fn(ext_dir))
        if index_from_result is not None:
            # name from the index the container actually captured (it can
            # advance past the caller's pre-check for concurrent SMs)
            final = self._key(
                shard_id, replica_id, index_from_result(result), suffix
            )
            if final != path:
                new_ext = os.path.join(
                    self._ext_root, final.replace("/", "_")
                )
                shutil.rmtree(new_ext, ignore_errors=True)
                os.rename(ext_dir, new_ext)
                path = final
        with self._lock:
            self._store[path] = buf.getvalue()
        return path, result

    def load(self, filepath: str) -> bytes:
        with self._lock:
            data = self._store.get(filepath)
        if data is None:
            raise FileNotFoundError(filepath)
        return data

    def open_read(self, filepath: str):
        return io.BytesIO(self.load(filepath))

    def external_path(self, filepath: str, name: str) -> str:
        return os.path.join(self._ext_dir(filepath), name)

    def file_size(self, filepath: str) -> int:
        return len(self.load(filepath))

    # -- _LeaseMixin hooks ----------------------------------------------
    def _lease_key(self, filepath: str) -> str:
        return filepath

    def _delete(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
        if self._ext_root is not None:
            shutil.rmtree(
                os.path.join(self._ext_root, key.replace("/", "_")),
                ignore_errors=True,
            )


class FileSnapshotStorage(_LeaseMixin):
    """Durable snapshot dirs with atomic finalize.

    Layout: <root>/snapshot-<shard>-<replica>-<index>/snapshot.bin
    (+ external-<id>-<name> siblings), written to a .generating temp dir,
    fsynced, then renamed — the rename is the commit point (reference:
    internal/fileutil [U]).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._init_leases()

    def _dir(
        self, shard_id: int, replica_id: int, index: int, suffix: str = ""
    ) -> str:
        name = f"snapshot-{shard_id}-{replica_id}-{index:020d}"
        if suffix:
            name += f"-{suffix}"
        return os.path.join(self.root, name)

    def _finalize(self, tmp: str, final: str) -> None:
        if os.path.exists(final):
            # leftover from an earlier incarnation of this replica id (the
            # rename below cannot clobber a non-empty dir)
            shutil.rmtree(final)
        os.rename(tmp, final)
        dfd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(dfd)  # make the rename itself durable
        finally:
            os.close(dfd)

    def _fresh_tmp(self, final: str) -> str:
        tmp = final + ".generating"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        return tmp

    def save(self, shard_id, replica_id, index, payload, suffix="") -> str:
        final = self._dir(shard_id, replica_id, index, suffix)
        tmp = self._fresh_tmp(final)
        fpath = os.path.join(tmp, "snapshot.bin")
        with open(fpath, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        self._finalize(tmp, final)
        return os.path.join(final, "snapshot.bin")

    def save_stream(
        self,
        shard_id: int,
        replica_id: int,
        index: int,
        build: Callable,
        suffix: str = "",
        index_from_result: Optional[Callable] = None,
    ):
        """``build(fileobj, copy_fn) -> result`` writes the container;
        ``copy_fn(file_id, src_path, metadata) -> SnapshotFile`` stages
        an external file beside it.  Atomic finalize after build; the
        final dir is named from ``index_from_result(result)`` when given
        (the container's captured index can advance past the caller's
        pre-check for concurrent SMs)."""
        final = self._dir(shard_id, replica_id, index, suffix)
        tmp = self._fresh_tmp(final)
        fpath = os.path.join(tmp, "snapshot.bin")
        with open(fpath, "wb") as f:
            result = build(f, _make_copy_fn(tmp))
            f.flush()
            os.fsync(f.fileno())
        if index_from_result is not None:
            final = self._dir(
                shard_id, replica_id, index_from_result(result), suffix
            )
        self._finalize(tmp, final)
        return os.path.join(final, "snapshot.bin"), result

    def load(self, filepath: str) -> bytes:
        """Whole-blob convenience load (tests, small in-mem flows);
        streaming consumers use ``open_read`` + bounded reads."""
        with open(filepath, "rb") as f:
            # raftlint: ignore[stream-read] bytes-level convenience API
            return f.read()

    def open_read(self, filepath: str):
        return open(filepath, "rb")

    def external_path(self, filepath: str, name: str) -> str:
        return os.path.join(os.path.dirname(filepath), name)

    def file_size(self, filepath: str) -> int:
        return os.path.getsize(filepath)

    # -- _LeaseMixin hooks ----------------------------------------------
    def _lease_key(self, filepath: str) -> str:
        return os.path.dirname(filepath)

    def _delete(self, key: str) -> None:
        if os.path.isdir(key):
            shutil.rmtree(key, ignore_errors=True)


# ---------------------------------------------------------------------------
# streaming source (sender) and receive sinks (receiver)
# ---------------------------------------------------------------------------
class SnapshotSource:
    """Sender-side handle for one outbound snapshot stream.

    Owns a GC lease on the snapshot dir for its lifetime, so the stream
    job can read incrementally long after the step worker moved on
    (reference: transport/job.go reading the snapshot inside the job,
    with snapshotter GC deferred [U]).
    """

    def __init__(self, storage, snapshot):
        from .snapshotio import SnapshotReader

        self._storage = storage
        self._lease = storage.lease(snapshot.filepath)
        self._lease.__enter__()
        self._closed = False
        try:
            self.main_path = snapshot.filepath
            self.main_size = storage.file_size(snapshot.filepath)
            with contextlib.closing(storage.open_read(snapshot.filepath)) as f:
                reader = SnapshotReader(f)  # validates meta + table
            self.externals = [
                (sf, storage.external_path(snapshot.filepath, sf.filepath))
                for sf in reader.external_files
            ]
        except BaseException:
            self.close()
            raise

    def open_main(self):
        return self._storage.open_read(self.main_path)

    def open_external(self, path: str):
        return open(path, "rb")

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lease.__exit__(None, None, None)


class _FileReceiveSink:
    """Incremental receiver: chunks land on disk as they arrive; the
    rename at finalize is the commit point."""

    def __init__(self, storage: "FileSnapshotStorage", final: str):
        self._storage = storage
        self._final = final
        self._tmp = storage._fresh_tmp(final)
        self._f = open(os.path.join(self._tmp, "snapshot.bin"), "wb")

    def write(self, data: bytes) -> None:
        self._f.write(data)

    def begin_external(self, name: str) -> None:
        base = os.path.basename(name)  # never trust wire paths
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = open(os.path.join(self._tmp, base), "wb")

    def validate(self) -> None:
        """Checksum-walk the received main container BEFORE finalize:
        a corrupted chunk that survived the wire must fail the receive
        (the sender retries) — finalizing it would fail-stop the
        replica at recover time instead.

        External files are stored VERBATIM with no per-file checksum
        (format parity with the reference), so only their SIZES can be
        cross-checked against the container's file table — truncated or
        padded external streams are rejected here, but a same-length
        bit flip in an external file is not detectable in this format.
        """
        from .snapshotio import SnapshotReader

        self._f.flush()
        with open(os.path.join(self._tmp, "snapshot.bin"), "rb") as f:
            reader = SnapshotReader(f)
            reader.validate()
        for sf in reader.external_files:
            p = os.path.join(self._tmp, os.path.basename(sf.filepath))
            got = os.path.getsize(p) if os.path.exists(p) else -1
            if got != sf.file_size:
                raise IOError(
                    f"external file {sf.filepath!r}: received {got} "
                    f"bytes, table says {sf.file_size}"
                )

    def finalize(self) -> str:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._storage._finalize(self._tmp, self._final)
        return os.path.join(self._final, "snapshot.bin")

    def abort(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass
        shutil.rmtree(self._tmp, ignore_errors=True)


class _MemReceiveSink:
    def __init__(self, storage: "InMemSnapshotStorage", key: str):
        self._storage = storage
        self._key = key
        self._main = io.BytesIO()
        self._cur = self._main
        self._ext_name: Optional[str] = None

    def write(self, data: bytes) -> None:
        self._cur.write(data)

    def begin_external(self, name: str) -> None:
        self._flush_ext()
        self._ext_name = os.path.basename(name)
        self._cur = io.BytesIO()

    def _flush_ext(self) -> None:
        if self._ext_name is not None:
            path = os.path.join(
                self._storage._ext_dir(self._key), self._ext_name
            )
            with open(path, "wb") as f:
                f.write(self._cur.getvalue())
            self._ext_name = None

    def validate(self) -> None:
        """Checksum-walk the received buffer when it IS a v2 container
        (trailer magic present) — same corrupt-chunk rejection as the
        file sink.  Transport-level tests stream raw non-container
        payloads through this sink; those skip validation."""
        import struct as _struct

        from .snapshotio import MAGIC, SnapshotReader

        buf = self._main.getvalue()
        # the format carries MAGIC in both header and trailer; either
        # one marks a container (a corrupt flip can kill at most one)
        is_container = len(buf) >= 8 and (
            _struct.unpack("<I", buf[:4])[0] == MAGIC
            or _struct.unpack("<I", buf[-4:])[0] == MAGIC
        )
        if not is_container:
            return  # raw payload (transport tests): nothing to checksum
        f = io.BytesIO(buf)
        SnapshotReader(f).validate()

    def finalize(self) -> str:
        self._flush_ext()
        with self._storage._lock:
            self._storage._store[self._key] = self._main.getvalue()
        return self._key

    def abort(self) -> None:
        pass


def _file_begin_receive(self, shard_id, replica_id, index, suffix=""):
    return _FileReceiveSink(self, self._dir(shard_id, replica_id, index, suffix))


def _mem_begin_receive(self, shard_id, replica_id, index, suffix=""):
    return _MemReceiveSink(self, self._key(shard_id, replica_id, index, suffix))


FileSnapshotStorage.begin_receive = _file_begin_receive
InMemSnapshotStorage.begin_receive = _mem_begin_receive
