"""tan: the durable segmented append-only LogDB.

reference: internal/tan/ — a log-structured LogDB (segmented append-only
log files + an in-memory index of live records), the v4 default,
designed to avoid general-KV write-amp for raft-log workloads [U].

Shape here: every ``save_raft_state`` batch appends crc-framed records
to the active segment and issues ONE fsync (the reference's
single-fsync-per-iteration contract); an ``InMemLogDB`` mirror holds
the live view for all reads.  At open, segments replay in order into
the mirror; a torn record at the tail of the LAST segment is the
crash point and replay stops there cleanly (any other corruption is an
error).  When enough closed segments accumulate, a checkpoint segment
is written that re-serializes only the live mirror state, and older
segments are deleted — crash-safe because replaying old segments then
the checkpoint converges to the same state as the checkpoint alone.
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from io import BytesIO
from typing import List, Optional

from ..logger import get_logger
from ..pb import MASK64, Bootstrap, Entry, Snapshot, State, Update
from ..raftio import ILogDB, NodeInfo
from ..transport.wire import (
    MAX_PAYLOAD,
    WireError,
    _R,
    _r_entry,
    _r_snapshot,
    _w_entry,
    _w_snapshot,
    bounded_decompress,
    maybe_compress,
)
from .journal import CorruptJournalError, scan_segment
from .logdb import InMemLogDB
from .vfs import DEFAULT as OS_VFS, IVFS, OSVFS

_log = get_logger("logdb")

_REC_HEADER = struct.Struct("<BII")  # kind, length, crc

K_STATE_ENTRIES = 1
K_SNAPSHOT = 2
K_BOOTSTRAP = 3
K_REMOVE_TO = 4
K_REMOVE_NODE = 5

# kind-byte flag: the record body is zlib-compressed (entry compression
# at the WAL level — reference: EntryCompression [U]; ours is adaptive:
# bodies over a threshold that actually shrink get the flag)
K_COMPRESSED = 0x80
COMPRESS_THRESHOLD = 512

_u64 = struct.Struct("<Q")

SEGMENT_PREFIX = "SEGMENT-"
DEFAULT_MAX_SEGMENT_BYTES = 64 * 1024 * 1024
DEFAULT_GC_SEGMENTS = 4


class CorruptLogError(CorruptJournalError):
    """Mid-log corruption (not a clean torn tail)."""


def _wu64(b: BytesIO, v: int) -> None:
    # mask, don't raise: uint64 wraparound parity (pb.MASK64 policy)
    b.write(_u64.pack(v & MASK64))


def _wb(b: BytesIO, v: bytes) -> None:
    b.write(struct.pack("<I", len(v)))
    b.write(v)


def _ws(b: BytesIO, v: str) -> None:
    _wb(b, v.encode("utf-8"))


def _encode_state_entries(u: Update) -> bytes:
    b = BytesIO()
    _wu64(b, u.shard_id)
    _wu64(b, u.replica_id)
    _wu64(b, u.state.term)
    _wu64(b, u.state.vote)
    _wu64(b, u.state.commit)
    b.write(struct.pack("<I", len(u.entries_to_save)))
    for e in u.entries_to_save:
        _w_entry(b, e)
    has_ss = not u.snapshot.is_empty()
    b.write(struct.pack("<B", int(has_ss)))
    if has_ss:
        _w_snapshot(b, u.snapshot)
    return b.getvalue()


def _encode_snapshot(shard_id: int, replica_id: int, ss: Snapshot) -> bytes:
    b = BytesIO()
    _wu64(b, shard_id)
    _wu64(b, replica_id)
    _w_snapshot(b, ss)
    return b.getvalue()


def _encode_bootstrap(shard_id: int, replica_id: int, bs: Bootstrap) -> bytes:
    b = BytesIO()
    _wu64(b, shard_id)
    _wu64(b, replica_id)
    b.write(struct.pack("<I", len(bs.addresses)))
    for rid in sorted(bs.addresses):
        _wu64(b, rid)
        _ws(b, bs.addresses[rid])
    b.write(struct.pack("<B", int(bs.join)))
    return b.getvalue()


def _encode_pair_index(shard_id: int, replica_id: int, index: int) -> bytes:
    b = BytesIO()
    _wu64(b, shard_id)
    _wu64(b, replica_id)
    _wu64(b, index)
    return b.getvalue()


def _encode_pair(shard_id: int, replica_id: int) -> bytes:
    b = BytesIO()
    _wu64(b, shard_id)
    _wu64(b, replica_id)
    return b.getvalue()


class TanLogDB(ILogDB):
    """Durable ILogDB: WAL segments + in-memory mirror."""

    def __init__(
        self,
        directory: str,
        *,
        max_segment_bytes: int = DEFAULT_MAX_SEGMENT_BYTES,
        gc_segments: int = DEFAULT_GC_SEGMENTS,
        use_native: Optional[bool] = None,
        compression: bool = True,
        fs: Optional[IVFS] = None,
    ):
        self.dir = directory
        self.max_segment_bytes = max_segment_bytes
        self.gc_segments = gc_segments
        self.compression = compression
        self.fs = fs if fs is not None else OS_VFS
        self._mirror = InMemLogDB()
        self._lock = threading.Lock()
        self._fh = None
        self._writer = None  # native group-commit writer (when available)
        if not isinstance(self.fs, OSVFS):
            # the native group-commit writer writes real files; a virtual
            # fs (crash simulation) must stay on the python writer
            if use_native:
                raise OSError("native walwriter needs the OS filesystem")
            use_native = False
        if use_native is None or use_native:
            from ..native import load_walwriter

            native_ok = load_walwriter() is not None
            if use_native and not native_ok:
                raise OSError("native walwriter requested but unavailable")
            self._use_native = native_ok
        else:
            self._use_native = False
        self._active_seq = 0
        self._active_bytes = 0
        self._inflight = 0  # native appends running outside the lock
        self._idle = threading.Condition(self._lock)  # inflight == 0
        self._rotate_pending = False  # gate: new appends wait, inflight drains
        # test-only fault injection (reference: vfs error-injection hooks
        # [U]): called with the framed bytes before every write+fsync on
        # BOTH writer paths (python and native group-commit); raising
        # simulates an I/O failure at that point
        self.fault_hook = None
        # the unified fault plane (faults.FaultController via a bound
        # adapter); consulted at the same write+fsync boundary
        self.fault_injector = None
        self.fs.makedirs(directory)
        self._replay()
        self._open_active()

    # -- segment plumbing -------------------------------------------------
    def _segments(self) -> List[int]:
        out = []
        for name in self.fs.listdir(self.dir):
            if name.startswith(SEGMENT_PREFIX) and name.endswith(".log"):
                try:
                    out.append(int(name[len(SEGMENT_PREFIX) : -4]))
                except ValueError:
                    pass
        return sorted(out)

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{SEGMENT_PREFIX}{seq:08d}.log")

    def _open_active(self) -> None:
        segs = self._segments()
        self._active_seq = (segs[-1] + 1) if segs else 1
        path = self._segment_path(self._active_seq)
        if self._use_native:
            from ..native import NativeWalWriter

            self._writer = NativeWalWriter(path)
            self._active_bytes = self._writer.size()
        else:
            self._fh = self.fs.open_append(path)
            self._active_bytes = self._fh.tell()
        self._sync_dir()

    def _close_active(self) -> None:
        if self._writer is not None:
            # clear the reference FIRST: if close() raises (I/O error),
            # a later append must see "no writer", not a dead handle
            w, self._writer = self._writer, None
            w.close()
        if self._fh is not None:
            fh, self._fh = self._fh, None
            fh.close()

    def _sync_dir(self) -> None:
        self.fs.sync_dir(self.dir)

    # -- replay -----------------------------------------------------------
    def _replay(self) -> None:
        segs = self._segments()
        for i, seq in enumerate(segs):
            last = i == len(segs) - 1
            self._replay_segment(self._segment_path(seq), torn_ok=last)

    def _replay_segment(self, path: str, torn_ok: bool) -> None:
        def apply(kind: int, body: bytes) -> None:
            if kind & K_COMPRESSED:
                kind &= ~K_COMPRESSED
                body = bounded_decompress(body, MAX_PAYLOAD)
            self._apply_record(kind, body)

        # shared scanner (storage/journal.py): torn-tail truncation +
        # crc/structure rules identical across the durable backends
        scan_segment(self.fs, path, self.dir, torn_ok, apply, CorruptLogError)

    def _apply_record(self, kind: int, body: bytes) -> None:
        r = _R(body)
        if kind == K_STATE_ENTRIES:
            shard_id, replica_id = r.u64(), r.u64()
            state = State(term=r.u64(), vote=r.u64(), commit=r.u64())
            entries = tuple(_r_entry(r) for _ in range(r.count()))
            ss = _r_snapshot(r) if r.u8() else Snapshot()
            u = Update(shard_id=shard_id, replica_id=replica_id)
            u.state = state
            u.entries_to_save = list(entries)
            u.snapshot = ss
            self._mirror.save_raft_state([u], 0)
        elif kind == K_SNAPSHOT:
            shard_id, replica_id = r.u64(), r.u64()
            ss = _r_snapshot(r)
            u = Update(shard_id=shard_id, replica_id=replica_id)
            u.snapshot = ss
            self._mirror.save_snapshots([u])
        elif kind == K_BOOTSTRAP:
            shard_id, replica_id = r.u64(), r.u64()
            addresses = {}
            for _ in range(r.count()):
                rid = r.u64()
                addresses[rid] = r.s()
            join = bool(r.u8())
            self._mirror.save_bootstrap_info(
                shard_id, replica_id, Bootstrap(addresses=addresses, join=join)
            )
        elif kind == K_REMOVE_TO:
            shard_id, replica_id, index = r.u64(), r.u64(), r.u64()
            self._mirror.remove_entries_to(shard_id, replica_id, index)
        elif kind == K_REMOVE_NODE:
            shard_id, replica_id = r.u64(), r.u64()
            self._mirror.remove_node_data(shard_id, replica_id)
        else:
            raise WireError(f"unknown record kind {kind}")

    # -- writes -----------------------------------------------------------
    def _frame(self, recs: List[tuple]) -> bytes:
        buf = BytesIO()
        for kind, body in recs:
            if self.compression:
                # max_out = the replay-side decompress bound: a compressed
                # oversize record would write fine and then make the WAL
                # permanently unopenable; stored raw it replays fine
                kind, body = maybe_compress(
                    kind, body, K_COMPRESSED, COMPRESS_THRESHOLD,
                    max_out=MAX_PAYLOAD,
                )
            buf.write(_REC_HEADER.pack(kind, len(body), zlib.crc32(body)))
            buf.write(body)
        return buf.getvalue()

    def _quiesce_appends_locked(self) -> None:
        """Wait (holding the lock) until no native append runs outside it.

        Every locked mutator that appends records must call this first:
        it restores the file-order == mirror-order invariant against the
        unlocked native save path, and makes writer swaps (rotate/close)
        safe."""
        while self._inflight:
            self._idle.wait()

    def _append_records(self, recs: List[tuple], sync: bool = True) -> None:
        """recs = [(kind, body)]; one write + one fsync for the batch.

        NEVER rotates: rotation may checkpoint-GC, which re-serializes
        the MIRROR — callers must publish the batch to the mirror first
        and then call ``_maybe_rotate``.  (Rotating in here once lost an
        acked batch: the checkpoint lacked it and GC deleted the segment
        holding its only durable copy — caught by the power-loss fuzz.)
        """
        raw = self._frame(recs)
        if self.fault_hook is not None:
            self.fault_hook(raw)
        if self.fault_injector is not None:
            self.fault_injector.on_fs_op("wal_append", self.dir)
        if self._writer is not None:
            # native path: write+fsync on the group-commit thread, GIL
            # released; concurrent workers' batches share one fsync
            self._writer.append(raw, sync=sync)
        else:
            self._fh.write(raw)
            if sync:
                self._fh.sync()
        self._active_bytes += len(raw)

    def _maybe_rotate(self) -> None:
        """Rotate once the active segment is full.  Only call with the
        mirror already reflecting every appended record (checkpoint GC
        serializes the mirror), and never under an in-flight append."""
        if (
            self._inflight == 0  # never swap the writer under an append
            and self._active_bytes >= self.max_segment_bytes
        ):
            self._rotate()

    def _rotate(self) -> None:
        self._close_active()
        self._open_active()
        closed = len(self._segments()) - 1
        if closed > self.gc_segments:
            self._checkpoint_gc()

    def _checkpoint_gc(self) -> None:
        """Re-serialize the live mirror into the new active segment and
        delete every older segment."""
        old = [s for s in self._segments() if s != self._active_seq]
        recs: List[tuple] = []
        with self._mirror._lock:
            for (shard_id, replica_id), ns in self._mirror._nodes.items():
                if ns.bootstrap is not None:
                    recs.append(
                        (
                            K_BOOTSTRAP,
                            _encode_bootstrap(shard_id, replica_id, ns.bootstrap),
                        )
                    )
                u = Update(shard_id=shard_id, replica_id=replica_id)
                u.state = ns.state
                u.entries_to_save = [
                    ns.entries[i] for i in sorted(ns.entries)
                ]
                u.snapshot = ns.snapshot
                recs.append((K_STATE_ENTRIES, _encode_state_entries(u)))
                if ns.min_index > 1:
                    recs.append(
                        (
                            K_REMOVE_TO,
                            _encode_pair_index(
                                shard_id, replica_id, ns.min_index - 1
                            ),
                        )
                    )
        # a checkpoint may itself exceed the segment cap; _append_records
        # never rotates, so it cannot recurse into another checkpoint
        self._append_records(recs, sync=True)
        self._sync_dir()
        for seq in old:
            try:
                self.fs.unlink(self._segment_path(seq))
            except OSError:
                pass
        self._sync_dir()

    # -- ILogDB -----------------------------------------------------------
    def name(self) -> str:
        return "tan"

    def close(self) -> None:
        with self._lock:
            self._quiesce_appends_locked()
            self._close_active()

    def list_node_info(self) -> List[NodeInfo]:
        return self._mirror.list_node_info()

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        with self._lock:
            self._quiesce_appends_locked()
            self._append_records(
                [(K_BOOTSTRAP, _encode_bootstrap(shard_id, replica_id, bootstrap))]
            )
            self._mirror.save_bootstrap_info(shard_id, replica_id, bootstrap)
            self._maybe_rotate()

    def get_bootstrap_info(self, shard_id, replica_id):
        return self._mirror.get_bootstrap_info(shard_id, replica_id)

    def save_raft_state(self, updates: List[Update], worker_id: int) -> None:
        recs = [
            (K_STATE_ENTRIES, _encode_state_entries(u)) for u in updates
        ]
        if self._writer is None:
            with self._lock:
                self._append_records(recs)  # ONE fsync for the whole batch
                self._mirror.save_raft_state(updates, worker_id)
                self._maybe_rotate()  # AFTER the mirror has the batch
            return
        # native path: the blocking (durable) append runs OUTSIDE the
        # lock so concurrent workers' batches group-commit into shared
        # fsyncs.  Per-shard record order is preserved (each shard is
        # stepped by exactly one worker); locked mutators for the same
        # shard quiesce in-flight appends first.
        raw = self._frame(recs)
        if self.fault_hook is not None:
            self.fault_hook(raw)
        if self.fault_injector is not None:
            self.fault_injector.on_fs_op("wal_append", self.dir)
        with self._lock:
            # a pending rotation blocks NEW appends so inflight can drain
            # — otherwise sustained load starves rotation (and GC) forever
            while self._rotate_pending:
                self._idle.wait()
            w = self._writer
            if w is None:
                raise OSError("logdb is closed")
            self._inflight += 1
        ok = False
        try:
            w.append(raw, sync=True)
            ok = True
        finally:
            with self._lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.notify_all()
                if ok:
                    # publish to readers only AFTER the bytes are durable
                    self._active_bytes += len(raw)
                    self._mirror.save_raft_state(updates, worker_id)
                    if (
                        self._active_bytes >= self.max_segment_bytes
                        and not self._rotate_pending
                    ):
                        self._rotate_pending = True
                        try:
                            self._quiesce_appends_locked()
                            self._rotate()
                        finally:
                            self._rotate_pending = False
                            self._idle.notify_all()

    def read_raft_state(self, shard_id, replica_id, last_index):
        return self._mirror.read_raft_state(shard_id, replica_id, last_index)

    def iterate_entries(self, shard_id, replica_id, low, high, max_size):
        return self._mirror.iterate_entries(
            shard_id, replica_id, low, high, max_size
        )

    def term(self, shard_id, replica_id, index):
        return self._mirror.term(shard_id, replica_id, index)

    def remove_entries_to(self, shard_id, replica_id, index) -> None:
        with self._lock:
            self._quiesce_appends_locked()
            self._append_records(
                [(K_REMOVE_TO, _encode_pair_index(shard_id, replica_id, index))],
                sync=False,  # compaction is advisory; replay just keeps more
            )
            self._mirror.remove_entries_to(shard_id, replica_id, index)
            self._maybe_rotate()

    def compact_entries_to(self, shard_id, replica_id, index) -> None:
        self.remove_entries_to(shard_id, replica_id, index)

    def save_snapshots(self, updates: List[Update]) -> None:
        recs = [
            (K_SNAPSHOT, _encode_snapshot(u.shard_id, u.replica_id, u.snapshot))
            for u in updates
            if not u.snapshot.is_empty()
        ]
        if not recs:
            return
        with self._lock:
            self._quiesce_appends_locked()
            self._append_records(recs)
            self._mirror.save_snapshots(updates)
            self._maybe_rotate()

    def get_snapshot(self, shard_id, replica_id) -> Snapshot:
        return self._mirror.get_snapshot(shard_id, replica_id)

    def remove_node_data(self, shard_id, replica_id) -> None:
        with self._lock:
            self._quiesce_appends_locked()
            self._append_records(
                [(K_REMOVE_NODE, _encode_pair(shard_id, replica_id))]
            )
            self._mirror.remove_node_data(shard_id, replica_id)
            self._maybe_rotate()

    def import_snapshot(self, snapshot: Snapshot, replica_id: int) -> None:
        with self._lock:
            self._quiesce_appends_locked()
            self._mirror.import_snapshot(snapshot, replica_id)
            ns = self._mirror._get(snapshot.shard_id, replica_id)
            u = Update(shard_id=snapshot.shard_id, replica_id=replica_id)
            u.state = ns.state
            u.snapshot = snapshot
            self._append_records(
                [
                    (K_STATE_ENTRIES, _encode_state_entries(u)),
                    (
                        K_REMOVE_TO,
                        _encode_pair_index(
                            snapshot.shard_id, replica_id, snapshot.index
                        ),
                    ),
                ]
            )
            self._maybe_rotate()


def tan_logdb_factory(config) -> TanLogDB:
    """NodeHostConfig.expert.logdb_factory hook."""
    base = config.wal_dir or config.nodehost_dir
    return TanLogDB(os.path.join(base, "tan"))
