"""Versioned, block-checksummed snapshot container (format v2).

reference: internal/rsm/snapshotio.go — SnapshotWriter/Reader with a
versioned header and the v2 block-CRC payload format [U].

Layout (little-endian):

    magic        u32  0x44425353 ("DBSS")
    version      u8   2
    compression  u8   CompressionType for sm-data blocks
    reserved     u16
    block_size   u32
    meta blob    [len u32 | crc u32 | bytes]   encode_rsm_snapshot(sm_data=None)
    sm blocks    repeated [stored_len u32 | crc u32 | flags u8 | bytes]
    sentinel     stored_len u32 == 0
    table blob   [len u32 | crc u32 | bytes]   external-file table
    trailer      sm_size u64 | table_off u64 | trailer_crc u32 | magic u32

Every section carries its own CRC, so corruption is DETECTED AND
LOCALIZED (bad meta vs bad block #k vs bad table), and the sm payload
streams through bounded memory in both directions: the writer buffers
one block, the reader verifies and yields one block at a time.
Compression is per block (flags bit0 = zlib, bit1 = snappy), so a
streamed save never materializes the whole payload either way.
"""
from __future__ import annotations

import struct
import zlib
from io import BytesIO
from typing import BinaryIO, List, Optional

from ..pb import MASK64, CompressionType, Membership, SnapshotFile
from ..transport.wire import (
    WireError,
    _R,
    _wb,
    _ws,
    _wu32,
    _wu64,
    _wu8,
    encode_rsm_snapshot,
    decode_rsm_snapshot,
)

MAGIC = 0x44425353
VERSION = 2
DEFAULT_BLOCK_SIZE = 1024 * 1024
MAX_BLOCK_SIZE = 64 * 1024 * 1024

BF_ZLIB = 1
BF_SNAPPY = 2

_u32 = struct.Struct("<I")
_trailer = struct.Struct("<QQII")  # sm_size, table_off, crc, magic


class SnapshotCorruptError(Exception):
    """Checksum/format failure, localized to a section."""


def _try_snappy():
    try:
        import snappy  # type: ignore

        return snappy
    except Exception:  # pragma: no cover - optional dependency
        return None


class SnapshotWriter:
    """Streaming container writer; file-like for the user SM's data.

    The SM writes through ``write`` (bounded buffering: one block);
    external files are registered with ``add_external_file``; ``close``
    finalizes sentinel + table + trailer.  The caller owns fsync.
    """

    def __init__(
        self,
        f: BinaryIO,
        *,
        index: int,
        term: int,
        membership: Membership,
        sessions: bytes,
        on_disk: bool,
        compression: int = int(CompressionType.NO_COMPRESSION),
        block_size: int = DEFAULT_BLOCK_SIZE,
    ):
        if not (0 < block_size <= MAX_BLOCK_SIZE):
            raise ValueError(f"bad block_size {block_size}")
        self._f = f
        self._block_size = block_size
        self._compression = int(compression)
        self._snappy = None
        if self._compression == int(CompressionType.SNAPPY):
            self._snappy = _try_snappy()
            if self._snappy is None:  # graceful degrade, like the wire path
                self._compression = int(CompressionType.ZLIB)
        self._buf = bytearray()
        self._sm_size = 0  # uncompressed sm-data bytes written
        self._files: List[SnapshotFile] = []
        self._closed = False
        f.write(struct.pack("<IBBH", MAGIC, VERSION, self._compression, 0))
        f.write(_u32.pack(block_size))
        meta = encode_rsm_snapshot(
            index=index,
            term=term,
            membership=membership,
            sessions=sessions,
            sm_data=None,
            on_disk=on_disk,
        )
        if len(meta) > MAX_BLOCK_SIZE:
            # the reader rejects oversized sections as corrupt; writing
            # one would produce an acked snapshot that can never be read
            # back (same bug class as the WAL compression bound)
            raise ValueError(
                f"snapshot meta section too large: {len(meta)} bytes "
                f"(sessions table?) > {MAX_BLOCK_SIZE}"
            )
        f.write(_u32.pack(len(meta)))
        f.write(_u32.pack(zlib.crc32(meta)))
        f.write(meta)
        self._pos = f.tell()

    # -- BinaryIO surface for the SM -----------------------------------
    def write(self, data) -> int:
        if self._closed:
            raise ValueError("writer is closed")
        self._buf += data
        while len(self._buf) >= self._block_size:
            self._emit_block(bytes(self._buf[: self._block_size]))
            del self._buf[: self._block_size]
        return len(data)

    def flush(self) -> None:  # SMs may call it; blocks flush on close
        pass

    def add_external_file(self, sf: SnapshotFile) -> None:
        if self._closed:
            raise ValueError("writer is closed")
        self._files.append(sf)

    @property
    def external_files(self) -> List[SnapshotFile]:
        return list(self._files)

    def _emit_block(self, raw: bytes) -> None:
        self._sm_size += len(raw)
        flags = 0
        body = raw
        if self._compression == int(CompressionType.ZLIB):
            z = zlib.compress(raw, 3)
            if len(z) < len(raw):
                flags, body = BF_ZLIB, z
        elif self._compression == int(CompressionType.SNAPPY):
            z = self._snappy.compress(raw)
            if len(z) < len(raw):
                flags, body = BF_SNAPPY, z
        self._f.write(_u32.pack(len(body)))
        self._f.write(_u32.pack(zlib.crc32(body)))
        self._f.write(struct.pack("<B", flags))
        self._f.write(body)

    def close(self) -> int:
        """Finalize; returns total container size in bytes."""
        if self._closed:
            raise ValueError("writer already closed")
        if self._buf:
            self._emit_block(bytes(self._buf))
            self._buf = bytearray()
        self._closed = True
        f = self._f
        f.write(_u32.pack(0))  # sentinel
        table_off = f.tell()
        b = BytesIO()
        _wu32(b, len(self._files))
        for sf in self._files:
            _wu64(b, sf.file_id)
            _ws(b, sf.filepath)
            _wu64(b, sf.file_size)
            _wb(b, sf.metadata)
        table = b.getvalue()
        if len(table) > MAX_BLOCK_SIZE:
            raise ValueError(
                f"external-file table too large: {len(table)} bytes"
            )
        f.write(_u32.pack(len(table)))
        f.write(_u32.pack(zlib.crc32(table)))
        f.write(table)
        head = struct.pack("<QQ", self._sm_size & MASK64, table_off & MASK64)
        f.write(head)
        f.write(_u32.pack(zlib.crc32(head)))
        f.write(_u32.pack(MAGIC))
        return f.tell()


class _SMStream:
    """Verified file-like view of the sm-data blocks."""

    def __init__(self, f: BinaryIO, start: int, snappy):
        self._f = f
        self._snappy = snappy
        self._pending = b""
        self._done = False
        self._block = 0
        f.seek(start)

    def read(self, n: int = -1) -> bytes:
        want = None if n is None or n < 0 else n
        chunks = [self._pending]
        have = len(self._pending)
        self._pending = b""
        while not self._done and (want is None or have < want):
            blk = self._next_block()
            if blk is None:
                self._done = True
                break
            chunks.append(blk)
            have += len(blk)
        data = b"".join(chunks)
        if want is not None and len(data) > want:
            data, self._pending = data[:want], data[want:]
        return data

    def _next_block(self) -> Optional[bytes]:
        hdr = self._f.read(4)
        if len(hdr) != 4:
            raise SnapshotCorruptError(
                f"truncated block header after block {self._block}"
            )
        (ln,) = _u32.unpack(hdr)
        if ln == 0:
            return None  # sentinel
        if ln > MAX_BLOCK_SIZE:
            raise SnapshotCorruptError(
                f"block {self._block}: absurd length {ln}"
            )
        rest = self._f.read(5 + ln)
        if len(rest) != 5 + ln:
            raise SnapshotCorruptError(f"block {self._block}: truncated body")
        (crc,) = _u32.unpack(rest[:4])
        flags = rest[4]
        body = rest[5:]
        if zlib.crc32(body) != crc:
            raise SnapshotCorruptError(
                f"block {self._block}: checksum mismatch"
            )
        if flags & BF_ZLIB:
            # bounded: a forged block must not expand past what a
            # legitimate writer could ever have produced (zip bomb)
            d = zlib.decompressobj()
            try:
                body = d.decompress(body, MAX_BLOCK_SIZE + 1)
            except zlib.error as e:
                raise SnapshotCorruptError(
                    f"block {self._block}: bad zlib stream: {e}"
                )
            if d.unconsumed_tail or len(body) > MAX_BLOCK_SIZE:
                raise SnapshotCorruptError(
                    f"block {self._block}: decompressed block exceeds "
                    f"{MAX_BLOCK_SIZE} bytes"
                )
        elif flags & BF_SNAPPY:
            if self._snappy is None:
                raise SnapshotCorruptError(
                    f"block {self._block}: snappy-compressed but snappy "
                    "is unavailable"
                )
            try:
                body = self._snappy.decompress(body)
            except Exception as e:
                raise SnapshotCorruptError(
                    f"block {self._block}: bad snappy stream: {e!r}"
                )
            if len(body) > MAX_BLOCK_SIZE:
                raise SnapshotCorruptError(
                    f"block {self._block}: decompressed block exceeds "
                    f"{MAX_BLOCK_SIZE} bytes"
                )
        self._block += 1
        return body


class SnapshotReader:
    """Container reader over a seekable binary file."""

    def __init__(self, f: BinaryIO):
        self._f = f
        hdr = f.read(12)
        if len(hdr) != 12:
            raise SnapshotCorruptError("truncated header")
        magic, version, compression, _ = struct.unpack("<IBBH", hdr[:8])
        (block_size,) = _u32.unpack(hdr[8:12])
        if magic != MAGIC:
            raise SnapshotCorruptError(f"bad magic {magic:#x}")
        if version != VERSION:
            raise SnapshotCorruptError(f"unsupported version {version}")
        self.compression = compression
        self.block_size = block_size
        mh = f.read(8)
        if len(mh) != 8:
            raise SnapshotCorruptError("truncated meta header")
        mlen, mcrc = struct.unpack("<II", mh)
        if mlen > MAX_BLOCK_SIZE:
            raise SnapshotCorruptError(f"absurd meta length {mlen}")
        meta = f.read(mlen)
        if len(meta) != mlen or zlib.crc32(meta) != mcrc:
            raise SnapshotCorruptError("meta section corrupt")
        try:
            d = decode_rsm_snapshot(meta)
        except (WireError, ValueError) as e:
            raise SnapshotCorruptError(f"meta decode: {e}")
        self.index = d["index"]
        self.term = d["term"]
        self.membership: Membership = d["membership"]
        self.sessions: bytes = d["sessions"]
        self.on_disk: bool = d["on_disk"]
        self._sm_start = f.tell()
        # trailer
        f.seek(0, 2)
        end = f.tell()
        if end < self._sm_start + 4 + _trailer.size:
            raise SnapshotCorruptError("truncated trailer")
        f.seek(end - _trailer.size)
        sm_size, table_off, tcrc, tmagic = _trailer.unpack(
            f.read(_trailer.size)
        )
        head = struct.pack("<QQ", sm_size & MASK64, table_off & MASK64)
        if tmagic != MAGIC or zlib.crc32(head) != tcrc:
            raise SnapshotCorruptError("trailer corrupt")
        self.sm_size = sm_size
        # external-file table
        f.seek(table_off)
        th = f.read(8)
        if len(th) != 8:
            raise SnapshotCorruptError("truncated table header")
        tlen, tbcrc = struct.unpack("<II", th)
        if tlen > MAX_BLOCK_SIZE:
            raise SnapshotCorruptError(f"absurd table length {tlen}")
        table = f.read(tlen)
        if len(table) != tlen or zlib.crc32(table) != tbcrc:
            raise SnapshotCorruptError("external-file table corrupt")
        r = _R(table)
        try:
            self.external_files: List[SnapshotFile] = [
                SnapshotFile(
                    file_id=r.u64(),
                    filepath=r.s(),
                    file_size=r.u64(),
                    metadata=r.blob(),
                )
                for _ in range(r.count())
            ]
        except (WireError, ValueError) as e:
            raise SnapshotCorruptError(f"table decode: {e}")
        self._snappy = _try_snappy()

    def sm_stream(self) -> _SMStream:
        return _SMStream(self._f, self._sm_start, self._snappy)

    def validate(self) -> int:
        """Walk every sm block, verifying checksums; returns sm byte
        size.  Localizes corruption to a block via the raised error."""
        s = self.sm_stream()
        total = 0
        while True:
            chunk = s.read(1 << 20)
            if not chunk:
                break
            total += len(chunk)
        if total != self.sm_size:
            raise SnapshotCorruptError(
                f"sm size mismatch: trailer says {self.sm_size}, "
                f"blocks held {total}"
            )
        return total
