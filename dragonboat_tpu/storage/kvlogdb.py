"""ShardedKVLogDB: the classic key-encoded LogDB over a general KV.

reference: internal/logdb/ (logdb.go, db.go, batched.go, plain.go,
cache.go, kv/kv.go) [U] — the pebble-backed default of v3: raft records
key-encoded into an ordered KV, N internal sub-stores partitioned by
shard id for lock/fsync parallelism, one atomic fsynced write batch per
``save_raft_state``, an entry read cache, and BOTH entry codecs:

  * ``plain``   — one entry per key (simple, larger key count)
  * ``batched`` — runs of entries packed per record, keyed at the run's
                  base index (the reference's 'hard' batched mode)

The KV itself (storage/kvstore.py) is journal+checkpoint based and runs
over the vfs layer, so the power-loss fuzz applies to this backend too.
"""
from __future__ import annotations

import struct
import threading
from collections import OrderedDict
from io import BytesIO
from typing import Dict, List, Optional, Tuple

from ..pb import MASK64, Bootstrap, Entry, Snapshot, State, Update
from ..raftio import ILogDB, NodeInfo, RaftState
from ..transport.wire import (
    _R,
    WireError,
    _r_entry,
    _r_snapshot,
    _w_entry,
    _w_snapshot,
)
from .kvstore import KVStore, WriteBatch
from .vfs import IVFS

K_STATE = 0x01
K_ENTRY = 0x02
K_BOOTSTRAP = 0x03
K_SNAPSHOT = 0x04
K_MININDEX = 0x05

_pair = struct.Struct(">BQQ")       # kind, shard, replica (big-endian sorts)
_entry_key = struct.Struct(">BQQQ")  # kind, shard, replica, index

MAX_INDEX = (1 << 63) - 1
DEFAULT_BATCH_SIZE = 64
DEFAULT_STORES = 4
CACHE_RECORDS = 512


def _pk(kind: int, shard_id: int, replica_id: int) -> bytes:
    return _pair.pack(kind, shard_id & MASK64, replica_id & MASK64)


def _ek(shard_id: int, replica_id: int, index: int) -> bytes:
    return _entry_key.pack(
        K_ENTRY, shard_id & MASK64, replica_id & MASK64, index & MASK64
    )


def _enc_entries(entries: List[Entry]) -> bytes:
    b = BytesIO()
    b.write(struct.pack("<I", len(entries)))
    for e in entries:
        _w_entry(b, e)
    return b.getvalue()


def _dec_entries(data: bytes) -> List[Entry]:
    r = _R(data)
    return [_r_entry(r) for _ in range(r.count())]


def _enc_state(st: State) -> bytes:
    return struct.pack(
        "<QQQ", st.term & MASK64, st.vote & MASK64, st.commit & MASK64
    )


def _dec_state(data: bytes) -> State:
    if len(data) != 24:
        raise WireError(f"state record must be 24 bytes, got {len(data)}")
    t, v, c = struct.unpack("<QQQ", data)
    return State(term=t, vote=v, commit=c)


def _enc_bootstrap(bs: Bootstrap) -> bytes:
    b = BytesIO()
    b.write(struct.pack("<I", len(bs.addresses)))
    for rid in sorted(bs.addresses):
        b.write(struct.pack("<Q", rid & MASK64))
        raw = bs.addresses[rid].encode("utf-8")
        b.write(struct.pack("<I", len(raw)))
        b.write(raw)
    b.write(struct.pack("<B", int(bs.join)))
    return b.getvalue()


def _dec_bootstrap(data: bytes) -> Bootstrap:
    r = _R(data)
    addresses = {r.u64(): r.s() for _ in range(r.count())}
    return Bootstrap(addresses=addresses, join=bool(r.u8()))


class ShardedKVLogDB(ILogDB):
    """ILogDB over N KVStore sub-stores, partitioned by shard id."""

    def __init__(
        self,
        directory: str,
        *,
        stores: int = DEFAULT_STORES,
        batched: bool = True,
        batch_size: int = DEFAULT_BATCH_SIZE,
        fs: Optional[IVFS] = None,
        max_journal_bytes: int = 32 * 1024 * 1024,
        gc_segments: int = 3,
    ):
        self.dir = directory
        self.batched = batched
        self.batch_size = batch_size if batched else 1
        self._stores = [
            KVStore(
                f"{directory}/store-{i:02d}",
                fs=fs,
                max_journal_bytes=max_journal_bytes,
                gc_segments=gc_segments,
            )
            for i in range(stores)
        ]
        # only the cache/version dicts need a lock: KVStore.commit is
        # internally atomic, and the engine guarantees per-shard
        # single-writer stepping (reference keeps per-sub-store locks;
        # a global write lock would serialize the sub-stores' fsyncs)
        self._cache_lock = threading.Lock()
        # decoded-record read cache, invalidated by a per-pair version
        # (reference: internal/logdb/cache.go [U])
        self._cache: "OrderedDict[tuple, List[Entry]]" = OrderedDict()
        self._versions: Dict[Tuple[int, int], int] = {}

    def _store(self, shard_id: int) -> KVStore:
        return self._stores[shard_id % len(self._stores)]

    def _bump(self, shard_id: int, replica_id: int) -> None:
        with self._cache_lock:
            k = (shard_id, replica_id)
            self._versions[k] = self._versions.get(k, 0) + 1

    def _ver(self, shard_id: int, replica_id: int) -> int:
        with self._cache_lock:
            return self._versions.get((shard_id, replica_id), 0)

    # -- ILogDB ----------------------------------------------------------
    def name(self) -> str:
        return "sharded-kv" + ("-batched" if self.batched else "-plain")

    def close(self) -> None:
        for s in self._stores:
            s.close()

    def list_node_info(self) -> List[NodeInfo]:
        out = []
        for s in self._stores:
            lo = struct.pack(">B", K_STATE)
            hi = struct.pack(">B", K_STATE + 1)
            for k, _ in s.iterate(lo, hi):
                _, shard_id, replica_id = _pair.unpack(k)
                out.append(NodeInfo(shard_id=shard_id, replica_id=replica_id))
        return sorted(out, key=lambda n: (n.shard_id, n.replica_id))

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        wb = WriteBatch()
        wb.put(_pk(K_BOOTSTRAP, shard_id, replica_id), _enc_bootstrap(bootstrap))
        # a bootstrap also registers the node (reference stores a state
        # record so ListNodeInfo finds never-started replicas [U?])
        st = self._store(shard_id)
        if st.get(_pk(K_STATE, shard_id, replica_id)) is None:
            wb.put(_pk(K_STATE, shard_id, replica_id), _enc_state(State()))
        st.commit(wb)

    def get_bootstrap_info(self, shard_id, replica_id):
        raw = self._store(shard_id).get(_pk(K_BOOTSTRAP, shard_id, replica_id))
        return _dec_bootstrap(raw) if raw is not None else None

    def save_raft_state(self, updates: List[Update], worker_id: int) -> None:
        """Atomic, durable, ONE fsync per sub-store touched — updates for
        different raft shards landing in the same sub-store share it
        (reference: cross-shard WriteBatch batching [U])."""
        batches: Dict[int, WriteBatch] = {}
        for u in updates:
            idx = (u.shard_id % len(self._stores))
            wb = batches.setdefault(idx, WriteBatch())
            self._encode_update(self._stores[idx], wb, u)
        for idx, wb in batches.items():
            self._stores[idx].commit(wb)
        for u in updates:
            # invalidate AFTER the commit: bumping first would let a
            # concurrent reader cache pre-commit bytes under the new
            # version and serve a replaced suffix forever
            self._bump(u.shard_id, u.replica_id)

    def _encode_update(self, store: KVStore, wb: WriteBatch, u: Update) -> None:
        s, r = u.shard_id, u.replica_id
        if not u.state.is_empty():
            wb.put(_pk(K_STATE, s, r), _enc_state(u.state))
        elif store.get(_pk(K_STATE, s, r)) is None:
            wb.put(_pk(K_STATE, s, r), _enc_state(State()))
        ents = u.entries_to_save
        if ents:
            first = ents[0].index
            # conflicting-suffix overwrite: drop every record that could
            # hold an entry >= first (batched records are keyed at their
            # base, so start the wipe one batch earlier)
            wipe_from = max(0, first - self.batch_size + 1)
            wb.delete_range(_ek(s, r, wipe_from), _ek(s, r, MAX_INDEX))
            # ...but re-save the prefix of the straddling batch (direct
            # record scan — NOT _read_entries, whose contiguity-from-low
            # contract returns nothing when `wipe_from` predates the log)
            if self.batched and wipe_from < first:
                keep = [
                    e
                    for _, v in store.iterate(
                        _ek(s, r, wipe_from), _ek(s, r, first)
                    )
                    for e in _dec_entries(v)
                    if e.index < first
                ]
                for i in range(0, len(keep), self.batch_size):
                    run = keep[i : i + self.batch_size]
                    wb.put(_ek(s, r, run[0].index), _enc_entries(run))
            for i in range(0, len(ents), self.batch_size):
                run = ents[i : i + self.batch_size]
                wb.put(_ek(s, r, run[0].index), _enc_entries(list(run)))
        if not u.snapshot.is_empty():
            b = BytesIO()
            _w_snapshot(b, u.snapshot)
            wb.put(_pk(K_SNAPSHOT, s, r), b.getvalue())

    # -- reads -----------------------------------------------------------
    def _read_entries(
        self, shard_id, replica_id, low, high, max_size=1 << 62
    ) -> List[Entry]:
        """Contiguous entries in [low, high) starting at low."""
        if high <= low:
            return []
        store = self._store(shard_id)
        scan_lo = _ek(shard_id, replica_id, max(0, low - self.batch_size + 1))
        scan_hi = _ek(shard_id, replica_id, high)
        ver = self._ver(shard_id, replica_id)
        out: List[Entry] = []
        size = 0
        nxt = low
        for k, v in store.iterate(scan_lo, scan_hi):
            ck = (k, ver)
            with self._cache_lock:
                ents = self._cache.get(ck)
                if ents is not None:
                    self._cache.move_to_end(ck)
            if ents is None:
                ents = _dec_entries(v)
                with self._cache_lock:
                    self._cache[ck] = ents
                    if len(self._cache) > CACHE_RECORDS:
                        self._cache.popitem(last=False)
            for e in ents:
                if e.index < nxt:
                    continue
                if e.index != nxt or e.index >= high:
                    return out  # gap (or past the window): stop
                size += e.size_bytes()
                if out and size > max_size:
                    return out
                out.append(e)
                nxt += 1
        return out

    def read_raft_state(self, shard_id, replica_id, last_index) -> Optional[RaftState]:
        store = self._store(shard_id)
        raw = store.get(_pk(K_STATE, shard_id, replica_id))
        if raw is None:
            return None
        ss = self.get_snapshot(shard_id, replica_id)
        min_raw = store.get(_pk(K_MININDEX, shard_id, replica_id))
        min_index = struct.unpack("<Q", min_raw)[0] if min_raw else 1
        first = max(min_index, ss.index + 1)
        # contiguous count from the record headers ALONE (each record's
        # <I count prefix + its base index in the key) — no body decode,
        # no read-cache thrash at startup for a large log
        count = 0
        nxt = first
        scan_lo = _ek(shard_id, replica_id,
                      max(0, first - self.batch_size + 1))
        for k, v in store.iterate(scan_lo, _ek(shard_id, replica_id, MAX_INDEX)):
            base = _entry_key.unpack(k)[3]
            (n,) = struct.unpack_from("<I", v, 0)
            if base > nxt:
                break  # gap
            if base + n <= nxt:
                continue  # fully below first (straddling prefix record)
            count += base + n - nxt
            nxt = base + n
        return RaftState(
            state=_dec_state(raw), first_index=first, entry_count=count
        )

    def iterate_entries(self, shard_id, replica_id, low, high, max_size):
        return self._read_entries(shard_id, replica_id, low, high, max_size)

    def term(self, shard_id, replica_id, index) -> Optional[int]:
        ents = self._read_entries(shard_id, replica_id, index, index + 1)
        if ents:
            return ents[0].term
        ss = self.get_snapshot(shard_id, replica_id)
        if ss.index == index and index > 0:
            return ss.term
        return None

    # -- compaction ------------------------------------------------------
    def remove_entries_to(self, shard_id, replica_id, index) -> None:
        store = self._store(shard_id)
        # the straddling batched record keeps its tail (direct record
        # scan — see the straddle note in _encode_update)
        keep: List[Entry] = []
        if self.batched:
            keep = [
                e
                for _, v in store.iterate(
                    _ek(shard_id, replica_id,
                        max(0, index - self.batch_size + 1)),
                    _ek(shard_id, replica_id, index + 1),
                )
                for e in _dec_entries(v)
                if e.index > index
            ]
        wb = WriteBatch()
        wb.delete_range(
            _ek(shard_id, replica_id, 0), _ek(shard_id, replica_id, index + 1)
        )
        for i in range(0, len(keep), self.batch_size):
            run = keep[i : i + self.batch_size]
            wb.put(_ek(shard_id, replica_id, run[0].index), _enc_entries(run))
        wb.put(
            _pk(K_MININDEX, shard_id, replica_id),
            struct.pack("<Q", (index + 1) & MASK64),
        )
        store.commit(wb, sync=False)  # advisory, like the tan path
        self._bump(shard_id, replica_id)  # invalidate AFTER the commit

    def compact_entries_to(self, shard_id, replica_id, index) -> None:
        self.remove_entries_to(shard_id, replica_id, index)

    # -- snapshots / membership -----------------------------------------
    def save_snapshots(self, updates: List[Update]) -> None:
        batches: Dict[int, WriteBatch] = {}
        for u in updates:
            if u.snapshot.is_empty():
                continue
            cur = self.get_snapshot(u.shard_id, u.replica_id)
            if u.snapshot.index <= cur.index:
                continue
            b = BytesIO()
            _w_snapshot(b, u.snapshot)
            idx = u.shard_id % len(self._stores)
            wb = batches.setdefault(idx, WriteBatch())
            wb.put(_pk(K_SNAPSHOT, u.shard_id, u.replica_id), b.getvalue())
        for idx, wb in batches.items():
            self._stores[idx].commit(wb)

    def get_snapshot(self, shard_id, replica_id) -> Snapshot:
        raw = self._store(shard_id).get(_pk(K_SNAPSHOT, shard_id, replica_id))
        if raw is None:
            return Snapshot()
        return _r_snapshot(_R(raw))

    def remove_node_data(self, shard_id, replica_id) -> None:
        wb = WriteBatch()
        for kind in (K_STATE, K_BOOTSTRAP, K_SNAPSHOT, K_MININDEX):
            wb.delete(_pk(kind, shard_id, replica_id))
        wb.delete_range(
            _ek(shard_id, replica_id, 0), _ek(shard_id, replica_id, MAX_INDEX)
        )
        self._store(shard_id).commit(wb)
        self._bump(shard_id, replica_id)  # invalidate AFTER the commit

    def import_snapshot(self, snapshot: Snapshot, replica_id: int) -> None:
        s = snapshot.shard_id
        wb = WriteBatch()
        b = BytesIO()
        _w_snapshot(b, snapshot)
        wb.put(_pk(K_SNAPSHOT, s, replica_id), b.getvalue())
        wb.put(
            _pk(K_STATE, s, replica_id),
            _enc_state(
                State(term=snapshot.term, vote=0, commit=snapshot.index)
            ),
        )
        wb.delete_range(
            _ek(s, replica_id, 0), _ek(s, replica_id, MAX_INDEX)
        )
        wb.put(
            _pk(K_MININDEX, s, replica_id),
            struct.pack("<Q", (snapshot.index + 1) & MASK64),
        )
        self._store(s).commit(wb)
        self._bump(s, replica_id)  # invalidate AFTER the commit


def kv_logdb_factory(config, **kw):
    """NodeHostConfig.expert.logdb_factory hook (classic KV backend)."""
    import os

    base = config.wal_dir or config.nodehost_dir
    return ShardedKVLogDB(os.path.join(base, "kvlogdb"), **kw)
