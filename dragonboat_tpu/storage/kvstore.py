"""A minimal embedded ordered KV store with durable atomic batches.

reference: internal/logdb/kv/kv.go -> IKVStore + the pebble binding
[U].  The reference's classic LogDB stores key-encoded raft records in
a general-purpose LSM KV; this is the same contract in miniature:

  * ordered byte-string keys, range iteration, range deletion
  * atomic durable write batches (ONE fsync per commit)
  * crash safety over the vfs layer (journal replay, torn-tail
    truncation, checkpoint-compaction GC — the discipline the tan WAL
    established, reused here and fuzzable on StrictMemFS)

Design: an in-memory ordered map (sorted key list + dict) backed by a
crc-framed journal.  When the journal outgrows a threshold, a CHECKPOINT
file is written with the full live state and older journal segments are
deleted; replay = newest checkpoint + journal tail.  This favors raft's
write-mostly access pattern without the weight of a full LSM tree.
"""
from __future__ import annotations

import bisect
import struct
import threading
from io import BytesIO
from typing import Dict, List, Optional, Tuple

from .journal import CorruptJournalError, frame_record, scan_segment
from .vfs import DEFAULT as OS_VFS, IVFS

OP_PUT = 1
OP_DELETE = 2
OP_DELETE_RANGE = 3
OP_CHECKPOINT_START = 4
OP_CHECKPOINT_END = 5
OP_BATCH = 6  # a whole WriteBatch in ONE crc-framed record (atomicity)

JOURNAL_PREFIX = "KV-"
DEFAULT_MAX_JOURNAL_BYTES = 32 * 1024 * 1024
DEFAULT_GC_SEGMENTS = 3


class CorruptKVError(CorruptJournalError):
    """Mid-journal corruption (not a clean torn tail)."""


_frame = frame_record


def _enc_kv(key: bytes, val: bytes) -> bytes:
    b = BytesIO()
    b.write(struct.pack("<I", len(key)))
    b.write(key)
    b.write(struct.pack("<I", len(val)))
    b.write(val)
    return b.getvalue()


def _dec_kv(body: bytes) -> Tuple[bytes, bytes]:
    (klen,) = struct.unpack_from("<I", body, 0)
    key = body[4 : 4 + klen]
    (vlen,) = struct.unpack_from("<I", body, 4 + klen)
    val = body[8 + klen : 8 + klen + vlen]
    if 8 + klen + vlen != len(body):
        raise CorruptKVError("kv record length mismatch")
    return key, val


class WriteBatch:
    """Atomic mutation set; applied + fsynced as one journal append."""

    __slots__ = ("ops",)

    def __init__(self):
        self.ops: List[Tuple[int, bytes, bytes]] = []

    def put(self, key: bytes, val: bytes) -> None:
        self.ops.append((OP_PUT, key, val))

    def delete(self, key: bytes) -> None:
        self.ops.append((OP_DELETE, key, b""))

    def delete_range(self, lo: bytes, hi: bytes) -> None:
        """Delete keys in [lo, hi)."""
        self.ops.append((OP_DELETE_RANGE, lo, hi))


class KVStore:
    """One journaled ordered map (a 'shard' of the sharded LogDB)."""

    def __init__(
        self,
        directory: str,
        *,
        fs: Optional[IVFS] = None,
        max_journal_bytes: int = DEFAULT_MAX_JOURNAL_BYTES,
        gc_segments: int = DEFAULT_GC_SEGMENTS,
    ):
        self.dir = directory
        self.fs = fs if fs is not None else OS_VFS
        self.max_journal_bytes = max_journal_bytes
        self.gc_segments = gc_segments
        self._lock = threading.Lock()
        self._map: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []  # sorted
        self._fh = None
        self._active_seq = 0
        self._active_bytes = 0
        self.fs.makedirs(directory)
        self._replay()
        self._open_active()

    # -- segments --------------------------------------------------------
    def _segments(self) -> List[int]:
        out = []
        for name in self.fs.listdir(self.dir):
            if name.startswith(JOURNAL_PREFIX) and name.endswith(".log"):
                try:
                    out.append(int(name[len(JOURNAL_PREFIX) : -4]))
                except ValueError:
                    pass
        return sorted(out)

    def _path(self, seq: int) -> str:
        return f"{self.dir}/{JOURNAL_PREFIX}{seq:08d}.log"

    def _open_active(self) -> None:
        segs = self._segments()
        self._active_seq = (segs[-1] + 1) if segs else 1
        self._fh = self.fs.open_append(self._path(self._active_seq))
        self._active_bytes = self._fh.tell()
        self.fs.sync_dir(self.dir)

    def _close_active(self) -> None:
        if self._fh is not None:
            fh, self._fh = self._fh, None
            fh.close()

    # -- replay ----------------------------------------------------------
    def _replay(self) -> None:
        self._ckpt_pending: Optional[Dict[bytes, bytes]] = None
        segs = self._segments()
        for i, seq in enumerate(segs):
            self._replay_segment(self._path(seq), torn_ok=i == len(segs) - 1)
            # a torn checkpoint (START without END) is discarded
            # wholesale: the pre-checkpoint state is intact because old
            # segments are only deleted AFTER the END record is durable.
            # Discard per SEGMENT — a checkpoint never spans segments,
            # so pending state at a segment boundary is always a tear.
            self._ckpt_pending = None

    def _replay_segment(self, path: str, torn_ok: bool) -> None:
        scan_segment(
            self.fs, path, self.dir, torn_ok, self._apply, CorruptKVError
        )

    def _apply(self, op: int, body: bytes) -> None:
        if op == OP_CHECKPOINT_START:
            # buffer the checkpoint: it only replaces the live map when
            # the END marker proves it was written completely
            self._ckpt_pending = {}
            return
        if op == OP_CHECKPOINT_END:
            if self._ckpt_pending is not None:
                self._map = dict(self._ckpt_pending)
                self._keys = sorted(self._map)
                self._ckpt_pending = None
            return
        if self._ckpt_pending is not None:
            if op == OP_PUT:
                key, val = _dec_kv(body)
                self._ckpt_pending[key] = val
                return
            raise CorruptKVError(f"op {op} inside a checkpoint")
        if op == OP_PUT:
            key, val = _dec_kv(body)
            self._put_mem(key, val)
        elif op == OP_DELETE:
            key, _ = _dec_kv(body)
            self._del_mem(key)
        elif op == OP_DELETE_RANGE:
            lo, hi = _dec_kv(body)
            self._del_range_mem(lo, hi)
        elif op == OP_BATCH:
            # the record boundary IS the atomicity boundary: a torn tail
            # drops the whole batch, never a prefix of it (reference:
            # pebble WriteBatch atomicity [U])
            pos, n = 0, len(body)
            while pos < n:
                sub = body[pos]
                (ln,) = struct.unpack_from("<I", body, pos + 1)
                self._apply(sub, body[pos + 5 : pos + 5 + ln])
                pos += 5 + ln
            if pos != n:
                raise CorruptKVError("batch record length mismatch")
        else:
            raise CorruptKVError(f"unknown op {op}")

    # -- in-memory ordered map ------------------------------------------
    def _put_mem(self, key: bytes, val: bytes) -> None:
        if key not in self._map:
            bisect.insort(self._keys, key)
        self._map[key] = val

    def _del_mem(self, key: bytes) -> None:
        if key in self._map:
            del self._map[key]
            i = bisect.bisect_left(self._keys, key)
            if i < len(self._keys) and self._keys[i] == key:
                del self._keys[i]

    def _del_range_mem(self, lo: bytes, hi: bytes) -> None:
        i = bisect.bisect_left(self._keys, lo)
        j = bisect.bisect_left(self._keys, hi)
        for k in self._keys[i:j]:
            del self._map[k]
        del self._keys[i:j]

    # -- public API ------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._map.get(key)

    def iterate(
        self, lo: bytes, hi: bytes
    ) -> List[Tuple[bytes, bytes]]:
        """Ordered (key, value) pairs with lo <= key < hi."""
        with self._lock:
            i = bisect.bisect_left(self._keys, lo)
            j = bisect.bisect_left(self._keys, hi)
            return [(k, self._map[k]) for k in self._keys[i:j]]

    def commit(self, batch: WriteBatch, sync: bool = True) -> None:
        """Apply + durably journal a batch: ONE crc-framed record and
        ONE fsync, so the batch is all-or-nothing across crashes
        (reference: a single fsynced pebble WriteBatch per
        SaveRaftState [U])."""
        body = BytesIO()
        for op, a, b in batch.ops:
            kv = _enc_kv(a, b)
            body.write(struct.pack("<BI", op, len(kv)))
            body.write(kv)
        raw = _frame(OP_BATCH, body.getvalue())
        with self._lock:
            self._fh.write(raw)
            if sync:
                self._fh.sync()
            for op, a, b in batch.ops:
                if op == OP_PUT:
                    self._put_mem(a, b)
                elif op == OP_DELETE:
                    self._del_mem(a)
                else:
                    self._del_range_mem(a, b)
            self._active_bytes += len(raw)
            # rotation AFTER the in-memory map reflects the batch: the
            # checkpoint serializes the map (same publish-then-rotate
            # rule the power-loss fuzz enforced on the tan WAL)
            if self._active_bytes >= self.max_journal_bytes:
                self._rotate()

    def _rotate(self) -> None:
        self._close_active()
        self._open_active()
        if len(self._segments()) - 1 > self.gc_segments:
            self._checkpoint_gc()

    def _checkpoint_gc(self) -> None:
        old = [s for s in self._segments() if s != self._active_seq]
        buf = BytesIO()
        buf.write(_frame(OP_CHECKPOINT_START, _enc_kv(b"", b"")))
        for k in self._keys:
            buf.write(_frame(OP_PUT, _enc_kv(k, self._map[k])))
        buf.write(_frame(OP_CHECKPOINT_END, _enc_kv(b"", b"")))
        raw = buf.getvalue()
        self._fh.write(raw)
        self._fh.sync()  # END is durable before any old segment dies
        self._active_bytes += len(raw)
        self.fs.sync_dir(self.dir)
        for seq in old:
            try:
                self.fs.unlink(self._path(seq))
            except OSError:
                pass
        self.fs.sync_dir(self.dir)

    def close(self) -> None:
        with self._lock:
            self._close_active()
