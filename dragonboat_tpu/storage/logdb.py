"""In-memory LogDB + the LogDB-backed log reader for the raft core.

reference: internal/logdb/ (ShardedDB) + internal/logdb/logreader.go [U].

``InMemLogDB`` implements the full ILogDB contract against process memory;
it is the storage backend for tests and for BASELINE config 1/2 (the
durable tan-style WAL lives in storage/tan.py).  A single instance may be
shared across NodeHost restarts to model "the disk" (as the reference's
tests do with MemFS).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..pb import Bootstrap, Entry, Snapshot, State, EMPTY_SNAPSHOT, Update
from ..raft.log import LogCompactedError, LogUnavailableError
from ..raftio import ILogDB, NodeInfo, RaftState


class _NodeStore:
    """Per-(shard,replica) record set."""

    def __init__(self):
        self.state = State()
        self.entries: Dict[int, Entry] = {}
        self.max_index = 0
        self.min_index = 1  # entries below were removed/compacted
        self.snapshot: Snapshot = EMPTY_SNAPSHOT
        self.bootstrap: Optional[Bootstrap] = None


def in_mem_logdb_factory(config) -> "InMemLogDB":
    """NodeHostConfig.expert.logdb_factory hook for the volatile backend.

    The default backend is the durable tan WAL; opting into process-memory
    storage must be explicit because a crash loses every acked write."""
    return InMemLogDB()


class InMemLogDB(ILogDB):
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[Tuple[int, int], _NodeStore] = {}
        self.sync_count = 0  # batched-write counter (1 per save_raft_state)
        # columnar hard-state lanes (ISSUE 13): replicas the device
        # merge tail saves every generation register a SLOT once
        # (state_lane_slot) and from then on save_state_slots persists
        # their (term, vote, commit) triples as THREE numpy scatters —
        # zero per-row Python on the hot save.  ``_hs_dirty[s]`` marks
        # lane words newer than ``ns.state``; readers and the classic
        # save path reconcile through _hs_sync (lane words materialize
        # into a State lazily, exactly-once).  All guarded by _lock.
        self._hs_slots: Dict[Tuple[int, int], int] = {}
        self._hs_next = 0  # monotone slot counter (slots of removed
        # replicas are orphaned, never reused — see remove_node_data)
        self._hs = np.zeros((3, 0), np.int64)
        self._hs_dirty = np.zeros((0,), bool)

    def _hs_sync(self, key, ns) -> None:  # guarded-by: _lock
        """Materialize pending lane words into ``ns.state`` (reader's
        half of the columnar protocol)."""
        s = self._hs_slots.get(key)
        if s is not None and self._hs_dirty[s]:
            self._hs_dirty[s] = False
            ns.state = State(
                term=int(self._hs[0, s]),
                vote=int(self._hs[1, s]),
                commit=int(self._hs[2, s]),
            )

    def state_lane_slot(self, shard_id: int, replica_id: int) -> int:
        """Register (or look up) the replica's hard-state lane slot.
        Callers cache the returned slot (engine: ``node.hs_lane_slot``)
        so the steady-state save path never touches the key dict."""
        with self._lock:
            key = (shard_id, replica_id)
            s = self._hs_slots.get(key)
            if s is None:
                s = self._hs_next
                self._hs_next = s + 1
                self._hs_slots[key] = s
                if s >= self._hs.shape[1]:
                    grow = max(64, 2 * self._hs.shape[1])
                    nb = np.zeros((3, grow), np.int64)
                    nb[:, : self._hs.shape[1]] = self._hs
                    self._hs = nb
                    nd = np.zeros((grow,), bool)
                    nd[: self._hs_dirty.shape[0]] = self._hs_dirty
                    self._hs_dirty = nd
            return s

    def save_state_slots(
        self, slots, terms, votes, commits, worker_id: int
    ) -> None:
        """Batched hard-state save by pre-registered slot: three numpy
        scatters + a dirty mark, one lock hold — the vectorized half of
        ILogDB.save_state_lanes for stores with a cheap hard-state
        column (atomicity contract is save_raft_state's)."""
        with self._lock:
            self._hs[0, slots] = terms
            self._hs[1, slots] = votes
            self._hs[2, slots] = commits
            self._hs_dirty[slots] = True
            self.sync_count += 1

    def _get(self, shard_id: int, replica_id: int) -> _NodeStore:
        key = (shard_id, replica_id)
        with self._lock:
            if key not in self._nodes:
                self._nodes[key] = _NodeStore()
            return self._nodes[key]

    # -- ILogDB ----------------------------------------------------------
    def name(self) -> str:
        return "inmem"

    def close(self) -> None:
        pass

    def list_node_info(self) -> List[NodeInfo]:
        with self._lock:
            return [
                NodeInfo(shard_id=s, replica_id=r) for (s, r) in self._nodes
            ]

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        with self._lock:
            self._get(shard_id, replica_id).bootstrap = bootstrap

    def get_bootstrap_info(self, shard_id, replica_id):
        with self._lock:
            return self._get(shard_id, replica_id).bootstrap

    def save_raft_state(self, updates: List[Update], worker_id: int) -> None:
        """One atomic batched write for all shards in ``updates`` —
        the reference's single-fsync-per-iteration trick
        (engine.go step worker -> logdb.SaveRaftState [U])."""
        with self._lock:
            for u in updates:
                ns = self._get(u.shard_id, u.replica_id)
                if not u.state.is_empty():
                    ns.state = u.state
                    if self._hs_slots:
                        # a classic save overrides pending lane words
                        s = self._hs_slots.get((u.shard_id, u.replica_id))
                        if s is not None:
                            self._hs_dirty[s] = False
                for e in u.entries_to_save:
                    ns.entries[e.index] = e
                    if e.index > ns.max_index:
                        ns.max_index = e.index
                if u.entries_to_save:
                    # overwrite truncates any conflicting suffix
                    last = u.entries_to_save[-1].index
                    for i in list(ns.entries):
                        if i > last:
                            del ns.entries[i]
                    ns.max_index = last
                if not u.snapshot.is_empty():
                    ns.snapshot = u.snapshot
                    if ns.max_index < u.snapshot.index:
                        ns.max_index = u.snapshot.index
            self.sync_count += 1

    def save_state_lanes(
        self, shard_ids, replica_ids, terms, votes, commits, worker_id
    ) -> None:
        """Batched hard-state-only save (see ILogDB.save_state_lanes):
        one lock hold, one State write per lane row, no per-row Update
        carrier — the in-memory store's half of the ISSUE-13 merge-tail
        vectorization."""
        with self._lock:
            get = self._get
            slots = self._hs_slots
            for s_id, r_id, t, v, c in zip(
                shard_ids, replica_ids, terms, votes, commits
            ):
                get(s_id, r_id).state = State(t, v, c)
                if slots:
                    s = slots.get((s_id, r_id))
                    if s is not None:
                        self._hs_dirty[s] = False
            self.sync_count += 1

    def read_raft_state(self, shard_id, replica_id, last_index) -> Optional[RaftState]:
        with self._lock:
            key = (shard_id, replica_id)
            if key not in self._nodes:
                # a replica saved ONLY through the columnar lane path
                # has no node store yet — pending lane words are still
                # durable state and must materialize through this
                # reader, not read back as None
                s = self._hs_slots.get(key)
                if s is None or not self._hs_dirty[s]:
                    return None
            ns = self._get(shard_id, replica_id)
            if self._hs_slots:
                self._hs_sync(key, ns)
            first = max(ns.min_index, ns.snapshot.index + 1)
            count = 0
            i = first
            while i in ns.entries:
                count += 1
                i += 1
            return RaftState(
                state=ns.state, first_index=first, entry_count=count
            )

    def iterate_entries(self, shard_id, replica_id, low, high, max_size) -> List[Entry]:
        with self._lock:
            ns = self._get(shard_id, replica_id)
            out: List[Entry] = []
            size = 0
            for i in range(low, high):
                e = ns.entries.get(i)
                if e is None:
                    break
                size += e.size_bytes()
                if out and size > max_size:
                    break
                out.append(e)
            return out

    def term(self, shard_id, replica_id, index) -> Optional[int]:
        with self._lock:
            ns = self._get(shard_id, replica_id)
            e = ns.entries.get(index)
            if e is not None:
                return e.term
            if ns.snapshot.index == index and index > 0:
                return ns.snapshot.term
            return None

    def remove_entries_to(self, shard_id, replica_id, index) -> None:
        with self._lock:
            ns = self._get(shard_id, replica_id)
            for i in list(ns.entries):
                if i <= index:
                    del ns.entries[i]
            ns.min_index = max(ns.min_index, index + 1)

    def compact_entries_to(self, shard_id, replica_id, index) -> None:
        self.remove_entries_to(shard_id, replica_id, index)

    def save_snapshots(self, updates: List[Update]) -> None:
        with self._lock:
            for u in updates:
                if not u.snapshot.is_empty():
                    ns = self._get(u.shard_id, u.replica_id)
                    if u.snapshot.index > ns.snapshot.index:
                        ns.snapshot = u.snapshot

    def get_snapshot(self, shard_id, replica_id) -> Snapshot:
        with self._lock:
            return self._get(shard_id, replica_id).snapshot

    def remove_node_data(self, shard_id, replica_id) -> None:
        with self._lock:
            self._nodes.pop((shard_id, replica_id), None)
            # orphan the hard-state lane slot: a re-added replica gets
            # a fresh slot (and a fresh _NodeStore); writes through a
            # stale cached slot land on the orphaned array column,
            # which no reader can reach once the key is popped
            self._hs_slots.pop((shard_id, replica_id), None)

    def import_snapshot(self, snapshot: Snapshot, replica_id: int) -> None:
        with self._lock:
            ns = self._get(snapshot.shard_id, replica_id)
            ns.snapshot = snapshot
            ns.state = State(
                term=snapshot.term, vote=0, commit=snapshot.index
            )
            s = self._hs_slots.get((snapshot.shard_id, replica_id))
            if s is not None:
                self._hs_dirty[s] = False
            ns.entries.clear()
            ns.max_index = snapshot.index
            ns.min_index = snapshot.index + 1


class LogDBLogReader:
    """ILogReader over an ILogDB for one (shard, replica) — keeps the
    log range in memory, reads entries/terms through the DB.

    reference: internal/logdb/logreader.go [U].  The node must call
    ``append``/``apply_snapshot``/``compact`` as it persists so the range
    stays accurate (terms/entries themselves always come from the DB).
    """

    def __init__(self, shard_id: int, replica_id: int, logdb: ILogDB):
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.logdb = logdb
        self._snapshot: Snapshot = EMPTY_SNAPSHOT
        self._marker = 1
        self._length = 0
        # term of the entry at marker-1 (the compaction boundary), kept so
        # prev-log-term checks right at the boundary still resolve — the
        # etcd-storage "dummy entry" trick (reference: logreader [U])
        self._marker_term: Optional[int] = None

    @classmethod
    def from_existing(
        cls, shard_id: int, replica_id: int, logdb: ILogDB
    ) -> Tuple["LogDBLogReader", Optional[State]]:
        """Open at restart: recover range + HardState (reference:
        nodehost loadState path [U])."""
        lr = cls(shard_id, replica_id, logdb)
        ss = logdb.get_snapshot(shard_id, replica_id)
        if not ss.is_empty():
            lr._snapshot = ss
            lr._marker = ss.index + 1
        rs = logdb.read_raft_state(shard_id, replica_id, 0)
        if rs is None:
            return lr, None
        if rs.entry_count > 0:
            lr._marker = rs.first_index
            lr._length = rs.entry_count
        elif not ss.is_empty():
            lr._marker = ss.index + 1
            lr._length = 0
        return lr, rs.state

    # -- ILogReader ------------------------------------------------------
    def log_range(self) -> Tuple[int, int]:
        if self._length > 0:
            # a locally created snapshot never hides live entries
            return self._marker, self._marker + self._length - 1
        first = max(self._marker, self._snapshot.index + 1)
        return first, first - 1

    def term(self, index: int) -> int:
        if index == self._snapshot.index and index > 0:
            return self._snapshot.term
        first, last = self.log_range()
        if index < first - 1:
            raise LogCompactedError(f"index {index} < first {first}")
        if index > last:
            raise LogUnavailableError(f"index {index} > last {last}")
        if index == 0:
            return 0
        t = self.logdb.term(self.shard_id, self.replica_id, index)
        if t is None:
            if index == self._marker - 1 and self._marker_term is not None:
                return self._marker_term
            raise LogUnavailableError(f"term missing at {index}")
        return t

    def entries(self, low: int, high: int, max_size: int) -> List[Entry]:
        first, last = self.log_range()
        if low < first:
            raise LogCompactedError(f"low {low} < first {first}")
        if high > last + 1:
            raise LogUnavailableError(f"high {high} > last+1 {last+1}")
        return self.logdb.iterate_entries(
            self.shard_id, self.replica_id, low, high, max_size
        )

    def snapshot(self) -> Snapshot:
        return self._snapshot

    # -- mutating half ----------------------------------------------------
    def append(self, entries: List[Entry]) -> None:
        if not entries:
            return
        first_new = entries[0].index
        last_cur = self._marker + self._length - 1
        if first_new > last_cur + 1:
            raise ValueError(f"log gap: {first_new} after {last_cur}")
        if first_new < self._marker:
            self._marker = first_new
            self._length = len(entries)
        else:
            self._length = first_new - self._marker + len(entries)

    def apply_snapshot(self, ss: Snapshot) -> None:
        """Restore: the log is reset to the snapshot point."""
        self._snapshot = ss
        self._marker = ss.index + 1
        self._length = 0
        self._marker_term = ss.term

    def create_snapshot(self, ss: Snapshot) -> None:
        """Record a locally created snapshot WITHOUT resetting the range —
        the log still holds entries past the snapshot (reference:
        logReader.CreateSnapshot vs ApplySnapshot [U])."""
        if ss.index > self._snapshot.index:
            self._snapshot = ss

    def compact(self, to_index: int) -> None:
        first, last = self.log_range()
        if to_index < self._marker:
            return
        keep_from = min(to_index + 1, last + 1)
        try:
            self._marker_term = self.term(keep_from - 1)
        except (LogCompactedError, LogUnavailableError):
            pass
        self._length -= keep_from - self._marker
        self._marker = keep_from
