"""Wire and state types for the TPU-native multi-group Raft framework.

This is the Python analogue of the reference's ``raftpb`` package
(reference: raftpb/raft.proto -> raft.pb.go [U] — see SURVEY.md provenance:
the reference mount was empty, citations are path-level reconstructions).

Design notes (TPU-first):
  * Every protocol scalar is an integer so that the hot subset of these
    types has a direct struct-of-arrays tensor encoding (see
    ``dragonboat_tpu.ops.state``).  ``MessageType`` values are stable and
    are used verbatim as the integer type-tags in the device message batch.
  * Dataclasses here are the host-side "scalar" view; the device-side view
    is the SoA pytree in ``ops/state.py``.  ``Update`` is the single I/O
    contract between the pure step function and the host runtime, exactly
    mirroring the reference's ``pb.Update`` (raftpb [U]).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# 64-bit width policy (docs/PARITY.md): every protocol integer is a
# uint64, as in the reference's raftpb.  Host structures carry Python's
# unbounded ints, so every serialization boundary masks with MASK64 —
# encode wraps like the reference's uint64 arithmetic instead of raising
# struct.error mid-persist.  raftlint's `width-64` rule pins the policy
# at the codec pack sites.
MASK64 = 0xFFFFFFFFFFFFFFFF


class MessageType(enum.IntEnum):
    """Raft message types (reference: raftpb MessageType enum [U]).

    The numeric values double as device-side type tags; the "hot set"
    (TICK..READ_INDEX_RESP) is handled by the vectorized kernel, the rest
    escalate to the host scalar path.
    """

    NO_OP = 0
    # --- hot set: handled by the TPU step kernel -------------------------
    LOCAL_TICK = 1
    ELECTION = 2              # local: campaign request (tick timeout fired)
    PROPOSE = 3               # local: client proposal (leader append)
    REPLICATE = 4             # MsgApp: leader -> follower entries
    REPLICATE_RESP = 5        # MsgAppResp
    REQUEST_VOTE = 6
    REQUEST_VOTE_RESP = 7
    REQUEST_PREVOTE = 8
    REQUEST_PREVOTE_RESP = 9
    HEARTBEAT = 10
    HEARTBEAT_RESP = 11
    READ_INDEX = 12           # local: client read hint
    READ_INDEX_RESP = 13
    # --- cold set: host scalar path --------------------------------------
    INSTALL_SNAPSHOT = 14
    SNAPSHOT_STATUS = 15      # local report: streaming result to leader
    SNAPSHOT_RECEIVED = 16
    UNREACHABLE = 17          # local report: transport failure
    LEADER_TRANSFER = 18      # local: admin request
    TIMEOUT_NOW = 19
    QUIESCE = 20
    CHECK_QUORUM = 21
    CONFIG_CHANGE_EVENT = 22  # local: apply/reject config change
    RATE_LIMIT = 23
    LEADER_HEARTBEAT = 24     # quiesce-exit poke
    BATCHED_READ_INDEX = 25


class EntryType(enum.IntEnum):
    """reference: raftpb EntryType [U]."""

    APPLICATION = 0
    CONFIG_CHANGE = 1
    ENCODED = 2      # client-compressed payload
    METADATA = 3     # empty entry appended on leader election


class ConfigChangeType(enum.IntEnum):
    """reference: raftpb ConfigChangeType [U] (v4 names)."""

    ADD_REPLICA = 0
    REMOVE_REPLICA = 1
    ADD_NON_VOTING = 2
    ADD_WITNESS = 3


class CompressionType(enum.IntEnum):
    NO_COMPRESSION = 0
    SNAPPY = 1
    ZLIB = 2  # the built-in codec (snappy needs the optional module)


NO_LEADER = 0
NO_NODE = 0


@dataclass(frozen=True)
class State:
    """Raft HardState — must be durable before messages are sent.

    reference: raftpb.State{Term, Vote, Commit} [U].
    """

    term: int = 0
    vote: int = 0
    commit: int = 0

    def is_empty(self) -> bool:
        return self.term == 0 and self.vote == 0 and self.commit == 0


EMPTY_STATE = State()


@dataclass(frozen=True)
class Entry:
    """A raft log entry (reference: raftpb.Entry [U]).

    ``key`` correlates a proposal with its pending future; ``client_id`` /
    ``series_id`` / ``responded_to`` implement exactly-once client sessions
    (reference: client/session.go [U]).
    """

    term: int = 0
    index: int = 0
    type: EntryType = EntryType.APPLICATION
    key: int = 0
    client_id: int = 0
    series_id: int = 0
    responded_to: int = 0
    cmd: bytes = b""

    def is_noop(self) -> bool:
        return (
            self.type == EntryType.APPLICATION
            and not self.cmd
            and self.client_id == 0
        )

    def is_config_change(self) -> bool:
        return self.type == EntryType.CONFIG_CHANGE

    def is_session_managed(self) -> bool:
        from .client import NOOP_SERIES_ID

        return self.client_id != 0 and self.series_id != NOOP_SERIES_ID

    def is_new_session_request(self) -> bool:
        from .client import SERIES_ID_REGISTER

        return (
            self.type == EntryType.APPLICATION
            and self.client_id != 0
            and self.series_id == SERIES_ID_REGISTER
        )

    def is_end_session_request(self) -> bool:
        from .client import SERIES_ID_UNREGISTER

        return (
            self.type == EntryType.APPLICATION
            and self.client_id != 0
            and self.series_id == SERIES_ID_UNREGISTER
        )

    def size_bytes(self) -> int:
        return len(self.cmd) + 64


@dataclass(frozen=True)
class Membership:
    """Group membership (reference: raftpb.Membership [U]).

    ``addresses`` maps voter replica-id -> target address; non_votings and
    witnesses likewise. ``removed`` is the tombstone set.
    """

    config_change_id: int = 0
    addresses: dict = field(default_factory=dict)       # replica_id -> addr
    non_votings: dict = field(default_factory=dict)
    witnesses: dict = field(default_factory=dict)
    removed: dict = field(default_factory=dict)         # replica_id -> True

    def copy(self) -> "Membership":
        return Membership(
            config_change_id=self.config_change_id,
            addresses=dict(self.addresses),
            non_votings=dict(self.non_votings),
            witnesses=dict(self.witnesses),
            removed=dict(self.removed),
        )


@dataclass(frozen=True)
class ConfigChange:
    """reference: raftpb.ConfigChange [U]."""

    config_change_id: int = 0
    type: ConfigChangeType = ConfigChangeType.ADD_REPLICA
    replica_id: int = 0
    address: str = ""
    initialize: bool = False


@dataclass(frozen=True)
class SnapshotFile:
    """External file attached to a snapshot (reference: raftpb.SnapshotFile [U])."""

    file_id: int = 0
    filepath: str = ""
    file_size: int = 0
    metadata: bytes = b""


@dataclass(frozen=True)
class Snapshot:
    """Snapshot metadata (reference: raftpb.Snapshot [U]).

    ``filepath`` points at the finalized snapshot dir/file on the host;
    ``dummy`` marks witness snapshots that carry no data.
    """

    filepath: str = ""
    file_size: int = 0
    index: int = 0
    term: int = 0
    membership: Membership = field(default_factory=Membership)
    files: Tuple[SnapshotFile, ...] = ()
    checksum: bytes = b""
    dummy: bool = False
    shard_id: int = 0
    replica_id: int = 0
    on_disk_index: int = 0       # on-disk SM: applied index at Open()
    witness: bool = False
    imported: bool = False
    type: int = 0
    compression: CompressionType = CompressionType.NO_COMPRESSION

    def is_empty(self) -> bool:
        return self.index == 0


EMPTY_SNAPSHOT = Snapshot()


@dataclass(frozen=True)
class ManifestFile:
    """One file of a portable snapshot archive (bigstate/dr.py): name
    relative to the archive dir, size, whole-file sha256 (hex) and the
    crc32 of each ``chunk_size`` slice — the import side verifies
    slices with bounded memory and localizes corruption to a chunk."""

    name: str = ""
    size: int = 0
    sha256: str = ""
    chunk_crcs: Tuple[int, ...] = ()


@dataclass(frozen=True)
class SnapshotManifest:
    """Self-describing metadata of a portable snapshot archive — the
    disaster-recovery interchange format (NodeHost.export_snapshot /
    import_snapshot; docs/BIGSTATE.md).  Serialized as MANIFEST.json by
    bigstate/dr.py so an archive is inspectable with nothing but a JSON
    reader; ``format_version`` gates future layout changes."""

    format_version: int = 1
    shard_id: int = 0
    replica_id: int = 0
    index: int = 0
    term: int = 0
    on_disk: bool = False
    chunk_size: int = 0
    compression: CompressionType = CompressionType.NO_COMPRESSION
    membership: Membership = field(default_factory=Membership)
    files: Tuple[ManifestFile, ...] = ()


@dataclass(frozen=True)
class Message:
    """A raft protocol message (reference: raftpb.Message [U]).

    ``log_term``/``log_index`` carry prevLogTerm/prevLogIndex for REPLICATE
    and the candidate's last log position for votes. ``hint``/``hint_high``
    carry the ReadIndex SystemCtx and the log-matching reject hint.

    ``trace_id``/``span_id`` are OBSERVABILITY context, not protocol
    state: a leader replicating a traced proposal stamps the proposal
    span's context onto the REPLICATE so the follower's append span
    stitches into the same cross-host trace (dragonboat_tpu.obs).  0
    means untraced; the raft core ignores both fields.
    """

    type: MessageType = MessageType.NO_OP
    to: int = 0
    from_: int = 0
    shard_id: int = 0
    term: int = 0
    log_term: int = 0
    log_index: int = 0
    commit: int = 0
    reject: bool = False
    hint: int = 0
    hint_high: int = 0
    entries: Tuple[Entry, ...] = ()
    snapshot: Snapshot = EMPTY_SNAPSHOT
    trace_id: int = 0
    span_id: int = 0

    def is_local(self) -> bool:
        return self.type in _LOCAL_TYPES

    def is_leader_message(self) -> bool:
        return self.type in (
            MessageType.REPLICATE,
            MessageType.INSTALL_SNAPSHOT,
            MessageType.HEARTBEAT,
            MessageType.TIMEOUT_NOW,
            MessageType.READ_INDEX_RESP,
        )


# Note: PROPOSE, READ_INDEX and LEADER_TRANSFER are NOT local — followers
# forward them to the leader over the wire (reference: isLocalMessageType [U]
# excludes forwardable types for the same reason).
_LOCAL_TYPES = frozenset(
    {
        MessageType.LOCAL_TICK,
        MessageType.ELECTION,
        MessageType.UNREACHABLE,
        MessageType.SNAPSHOT_STATUS,
        MessageType.SNAPSHOT_RECEIVED,
        MessageType.CHECK_QUORUM,
        MessageType.CONFIG_CHANGE_EVENT,
        MessageType.RATE_LIMIT,
        MessageType.QUIESCE,
        MessageType.BATCHED_READ_INDEX,
    }
)


@dataclass(frozen=True)
class SystemCtx:
    """ReadIndex correlation hint (reference: raftpb.SystemCtx [U])."""

    low: int = 0
    high: int = 0


@dataclass(frozen=True)
class ReadyToRead:
    """ReadIndex confirmation (reference: raftpb.ReadyToRead [U])."""

    index: int = 0
    system_ctx: SystemCtx = field(default_factory=SystemCtx)


@dataclass(frozen=True)
class UpdateCommit:
    """Cursor advances applied by ``peer.commit`` after the host has
    consumed an Update (reference: raftpb.UpdateCommit [U])."""

    processed: int = 0           # committed entries handed to apply
    last_applied: int = 0
    stable_log_index: int = 0    # in-memory log persisted up to here
    stable_log_term: int = 0
    stable_snapshot_index: int = 0
    ready_to_read: int = 0


@dataclass
class Update:
    """The entire I/O contract between the pure raft core and the host
    runtime (reference: raftpb.Update [U]; peer.GetUpdate).

    Host obligations, in order (matches the reference engine):
      1. persist ``state`` + ``entries_to_save`` + ``snapshot`` (fsync)
      2. send ``messages``
      3. hand ``committed_entries`` to the apply loop
      4. surface ``ready_to_reads``
      5. call ``peer.commit(update)`` to advance cursors
    """

    shard_id: int = 0
    replica_id: int = 0
    state: State = EMPTY_STATE
    entries_to_save: List[Entry] = field(default_factory=list)
    committed_entries: List[Entry] = field(default_factory=list)
    messages: List[Message] = field(default_factory=list)
    last_applied: int = 0
    snapshot: Snapshot = EMPTY_SNAPSHOT
    ready_to_reads: List[ReadyToRead] = field(default_factory=list)
    dropped_entries: List[Entry] = field(default_factory=list)
    dropped_read_indexes: List[SystemCtx] = field(default_factory=list)
    update_commit: UpdateCommit = field(default_factory=UpdateCommit)
    fast_apply: bool = False
    has_update: bool = False

    def has_work(self) -> bool:
        return (
            self.has_update
            or bool(self.entries_to_save)
            or bool(self.committed_entries)
            or bool(self.messages)
            or bool(self.ready_to_reads)
            or not self.snapshot.is_empty()
        )


# message-batch wire format version (reference: raftio TransportBinVersion
# [U]).  v1: every Message carries a trace-context flag byte (+ ids when
# traced) after the snapshot field.  decode_batch still reads v0 (no
# flag byte — rolling upgrades keep talking) and rejects unknown FUTURE
# versions loudly instead of shifting every subsequent field into
# garbage; the encoder always emits the current version.
MESSAGE_BATCH_BIN_VER = 1


@dataclass(frozen=True)
class MessageBatch:
    """Coalesced wire unit between hosts (reference: raftpb.MessageBatch [U])."""

    messages: Tuple[Message, ...] = ()
    source_address: str = ""
    deployment_id: int = 0
    bin_ver: int = MESSAGE_BATCH_BIN_VER


@dataclass(frozen=True)
class Chunk:
    """One snapshot chunk on the wire (reference: raftpb.Chunk [U])."""

    shard_id: int = 0
    replica_id: int = 0
    from_: int = 0
    chunk_id: int = 0
    chunk_size: int = 0
    chunk_count: int = 0
    index: int = 0
    term: int = 0
    # the carrying InstallSnapshot message's term (the raft term gate on the
    # receiver needs it; chunk.term above is the snapshot's log term)
    message_term: int = 0
    data: bytes = b""
    membership: Membership = field(default_factory=Membership)
    filepath: str = ""
    file_size: int = 0
    file_chunk_id: int = 0
    file_chunk_count: int = 0
    has_file_info: bool = False
    file_info: SnapshotFile = field(default_factory=SnapshotFile)
    bin_ver: int = 0
    deployment_id: int = 0
    witness: bool = False
    dummy: bool = False
    on_disk_index: int = 0


@dataclass(frozen=True)
class Bootstrap:
    """First-boot record (reference: raftpb.Bootstrap [U])."""

    addresses: dict = field(default_factory=dict)
    join: bool = False
    smtype: int = 0


@dataclass(frozen=True)
class RaftDataStatus:
    """LogDB format self-description (reference: raftio BinaryFormat [U])."""

    address: str = ""
    bin_ver: int = 0
    hard_hash: int = 0
    logdb_type: str = ""
    hostname: str = ""
    deployment_id: int = 0
