"""Execution engines (reference: engine.go execEngine [U]).

``ExecEngine`` is the host engine: fixed worker pools stepping many shards
with cross-shard batched WAL writes.  The TPU step engine
(dragonboat_tpu.engine.tpu_engine) plugs in via
``ExpertConfig.step_engine_factory``.
"""
from .execengine import ExecEngine, IStepEngine

__all__ = ["ExecEngine", "IStepEngine"]
