"""The host execution engine: fixed worker pools over all shards.

reference: engine.go [U].  The shape is the reference's exactly:

  * shards are partitioned by ``shard_id % worker_count``;
  * each **step worker** drains its ready set, calls ``node.step()`` for
    each ready shard, then issues ONE batched ``logdb.save_raft_state``
    for all their Updates (the single-fsync-per-iteration trick), then
    ``node.process_update`` per shard (send + schedule apply);
  * **apply workers** drain ``rsm.TaskQueue``s;
  * ``WorkReady`` is the per-partition ready-set + condition pair so idle
    shards cost nothing.

This is also the "StepEngineFactory" seam: a vectorized engine replaces
the per-shard ``node.step()`` loop with one device call over the whole
partition (see engine/tpu_engine.py).
"""
from __future__ import annotations

import abc
import threading
import time
from typing import Dict, List, Optional, TYPE_CHECKING

from ..logger import get_logger
from ..metrics import MetricsRegistry
from ..utils.stopper import Stopper

if TYPE_CHECKING:
    from ..node import Node

_log = get_logger("engine")


class WorkReady:
    """Per-partition ready-shard set with wakeup (reference: workReady [U])."""

    def __init__(self, partitions: int):
        self.partitions = partitions
        self._sets: List[set] = [set() for _ in range(partitions)]
        self._conds = [threading.Condition() for _ in range(partitions)]

    def partition(self, shard_id: int) -> int:
        return shard_id % self.partitions

    def notify(self, shard_id: int) -> None:
        p = self.partition(shard_id)
        with self._conds[p]:
            self._sets[p].add(shard_id)
            self._conds[p].notify()

    def notify_all(self, shard_ids) -> None:
        by_p: Dict[int, List[int]] = {}
        for s in shard_ids:
            by_p.setdefault(self.partition(s), []).append(s)
        for p, ids in by_p.items():
            with self._conds[p]:
                self._sets[p].update(ids)
                self._conds[p].notify()

    def wait(self, p: int, timeout: float, stop: threading.Event) -> List[int]:
        with self._conds[p]:
            if not self._sets[p] and not stop.is_set():
                self._conds[p].wait(timeout)
            out = list(self._sets[p])
            self._sets[p].clear()
            return out

    def wake(self) -> None:
        for c in self._conds:
            with c:
                c.notify_all()


class IStepEngine(abc.ABC):
    """The sanctioned plug point (north star: StepEngineFactory beside
    LogDBFactory/TransportFactory under ExpertConfig)."""

    @abc.abstractmethod
    def step_shards(self, nodes: List["Node"], worker_id: int) -> None:
        """Step every node, batch-persist, dispatch."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    def detach(self, shard_id: int) -> None:
        """A shard was unregistered; release any engine-held row state."""

    def detach_many(self, shard_ids) -> None:
        """Batch detach (NodeHost.close): engines holding shared state
        behind one lock override this so a 10k-shard teardown is one
        lock acquisition, not 10k interleaved with live launches."""
        for s in shard_ids:
            self.detach(s)

    def device_coordinate(self, shard_id: int):
        """Device/chip coordinate hosting this shard's engine row, or
        None when unknown (host path, no mesh).  Mesh-capable engines
        override (VectorStepEngine); the balance plane reads it through
        ExecEngine so chip placement becomes a planner dimension
        (ROADMAP 3 / docs/MULTICHIP.md "Placement")."""
        return None

    def device_chip_count(self) -> int:
        """Chips this engine spreads rows over (1 = single device)."""
        return 1


class HostStepEngine(IStepEngine):
    """Default serial step loop with cross-shard batched WAL writes."""

    def __init__(self, logdb):
        self.logdb = logdb

    def step_shards(self, nodes: List["Node"], worker_id: int) -> None:
        updates = []
        stepped = []
        for node in nodes:
            u = node.step()
            if u is not None:
                updates.append(u)
                stepped.append((node, u))
        if not updates:
            return
        # one batched fsync for every shard stepped this iteration
        self.logdb.save_raft_state(updates, worker_id)
        for node, u in stepped:
            if node.process_update(u):
                node.engine_apply_ready(node.shard_id)  # type: ignore[attr-defined]


class ExecEngine:
    def __init__(
        self,
        logdb,
        step_workers: int = 16,
        apply_workers: int = 16,
        step_engine: Optional[IStepEngine] = None,
        metrics=None,
    ):
        self.logdb = logdb
        # a disabled registry no-ops every record call, so the worker
        # loop needs no metrics-enabled branch; resolve the instruments
        # once — the step loop is hot
        self.metrics = metrics or MetricsRegistry(enabled=False)
        self._step_hist = self.metrics.histogram("raft_engine_step_seconds")
        self._step_iters = self.metrics.counter(
            "raft_engine_step_iterations_total"
        )
        # obs tentpole: the step-batch-size distribution is THE signal
        # separating "many idle wakeups" from "healthy batching" (the
        # single-fsync-per-iteration trick only pays when batches > 1);
        # bucket bounds are shard counts, not seconds
        self._step_batch_hist = self.metrics.histogram(
            "raft_engine_step_batch_size",
            bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        )
        self._apply_hist = self.metrics.histogram("raft_engine_apply_seconds")
        self.step_ready = WorkReady(step_workers)
        self.apply_ready = WorkReady(apply_workers)
        self.step_engine = step_engine or HostStepEngine(logdb)
        self._nodes: Dict[int, "Node"] = {}  # shard_id -> node
        self._nodes_lock = threading.RLock()
        # owned-thread lifecycle (reference: syncutil.Stopper [U]):
        # stop() signals + joins every worker and reports stragglers
        self._stopper = Stopper("tpu-raft-engine")
        self._stop = self._stopper.should_stop
        self._worker_plan = [
            (self._step_worker_main, f"tpu-raft-step-{i}")
            for i in range(step_workers)
        ] + [
            (self._apply_worker_main, f"tpu-raft-apply-{i}")
            for i in range(apply_workers)
        ]

    def start(self) -> None:
        self.step_engine.start()
        for i, (fn, name) in enumerate(self._worker_plan):
            wid = int(name.rsplit("-", 1)[1])
            self._stopper.run_worker(lambda f=fn, w=wid: f(w), name)

    def stop(self) -> None:
        self._stop.set()
        self.step_ready.wake()
        self.apply_ready.wake()
        # the join must outlast one worst-case step iteration: in
        # colocated mode a worker can be blocked on the shared core lock
        # behind another member's full-width launch (multi-second at 64k
        # rows on CPU) — 2s here is what produced the r03 MULTICHIP
        # 'workers leaked at stop' artifact.  The join returns the
        # moment workers exit, so a healthy stop stays fast.
        leaked = self._stopper.stop(timeout=30.0)
        if leaked:
            _log.warning("engine workers leaked at stop: %s", leaked)
        self.step_engine.stop()

    # -- registration -----------------------------------------------------
    def register(self, node: "Node") -> None:
        # callbacks must be in place before the node is visible to workers:
        # a stale workReady entry for this shard id can step it immediately
        node.notify_work = lambda s=node.shard_id: self.step_ready.notify(s)
        node.engine_apply_ready = lambda s: self.apply_ready.notify(s)
        # the WorkReady itself, for the batched per-SM-worker commit
        # handoff (ops/engine._apply_lane_commits): one notify_all per
        # partition per generation instead of one lock take per row
        node.apply_work_ready = self.apply_ready
        with self._nodes_lock:
            self._nodes[node.shard_id] = node
        self.step_ready.notify(node.shard_id)

    def unregister(self, shard_id: int) -> None:
        with self._nodes_lock:
            self._nodes.pop(shard_id, None)
        self.step_engine.detach(shard_id)

    def unregister_many(self, shard_ids) -> None:
        with self._nodes_lock:
            for s in shard_ids:
                self._nodes.pop(s, None)
        self.step_engine.detach_many(shard_ids)

    def nodes_for_partition(self, shard_ids: List[int]) -> List["Node"]:
        with self._nodes_lock:
            return [
                self._nodes[s]
                for s in shard_ids
                if s in self._nodes and not self._nodes[s].stopped
            ]

    def notify(self, shard_id: int) -> None:
        self.step_ready.notify(shard_id)

    # -- placement -> device coordinate (the balance plane's chip axis) --
    def device_coordinate(self, shard_id: int):
        return self.step_engine.device_coordinate(shard_id)

    def device_chip_count(self) -> int:
        return self.step_engine.device_chip_count()

    def notify_many(self, shard_ids) -> None:
        self.step_ready.notify_all(shard_ids)

    # -- workers ----------------------------------------------------------
    def _step_worker_main(self, worker_id: int) -> None:
        while not self._stop.is_set():
            ready = self.step_ready.wait(worker_id, timeout=0.1, stop=self._stop)
            if self._stop.is_set():
                return
            nodes = self.nodes_for_partition(ready)
            if not nodes:
                continue
            try:
                t0 = time.perf_counter()
                self.step_engine.step_shards(nodes, worker_id)
                self._step_hist.observe(time.perf_counter() - t0)
                self._step_batch_hist.observe(len(nodes))
                self._step_iters.add()
            except Exception:  # noqa: BLE001
                _log.exception("step worker %d failed", worker_id)
            # shards with remaining work re-arm immediately
            for n in nodes:
                if n.has_work():
                    self.step_ready.notify(n.shard_id)

    def _apply_worker_main(self, worker_id: int) -> None:
        while not self._stop.is_set():
            ready = self.apply_ready.wait(worker_id, timeout=0.1, stop=self._stop)
            if self._stop.is_set():
                return
            with self._nodes_lock:
                nodes = [self._nodes[s] for s in ready if s in self._nodes]
            for node in nodes:
                try:
                    t0 = time.perf_counter()
                    node.apply()
                    self._apply_hist.observe(time.perf_counter() - t0)
                except Exception:  # noqa: BLE001
                    _log.exception(
                        "apply worker %d shard %d failed", worker_id, node.shard_id
                    )
                # applying may have unblocked step work (e.g. config change)
                if node.has_work():
                    self.step_ready.notify(node.shard_id)
