// Native WAL writer with group commit.
//
// reference: dragonboat's LogDB commits many shards' updates with one
// batched fsync per step-worker iteration (engine.go -> SaveRaftState
// [U]).  This writer extends that batching ACROSS worker threads: all
// appends that arrive while an fsync is in flight are coalesced into
// the next single write+fsync, and every caller blocks only until its
// own bytes are durable.  Python callers enter through ctypes, which
// releases the GIL for the duration — so a slow fsync never stalls the
// interpreter.
//
// Build: g++ -O2 -shared -fPIC -pthread -o libwalwriter.so walwriter.cpp
//
// Exposed C ABI (see native/__init__.py for the ctypes binding):
//   wal_open(path)                -> handle (NULL on error)
//   wal_append(h, buf, len, sync) -> total bytes appended so far, or -1
//   wal_size(h)                   -> bytes appended
//   wal_sync(h)                   -> 0 once everything queued is durable
//   wal_close(h)                  -> 0 (flushes + fsyncs first)

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>

namespace {

struct Wal {
  int fd = -1;
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  std::string pending;       // bytes queued but not yet written
  uint64_t queued_seq = 0;   // ticket of the newest queued batch
  uint64_t synced_seq = 0;   // newest ticket known durable
  int64_t total = 0;         // bytes appended (queued + written)
  bool stop = false;
  bool io_error = false;
  std::thread syncer;

  void run() {
    std::string batch;
    for (;;) {
      uint64_t seq;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stop || !pending.empty(); });
        if (pending.empty() && stop) return;
        batch.swap(pending);
        seq = queued_seq;
      }
      bool ok = true;
      const char* p = batch.data();
      size_t left = batch.size();
      while (left > 0) {
        ssize_t n = ::write(fd, p, left);
        if (n < 0) {
          if (errno == EINTR) continue;
          ok = false;
          break;
        }
        p += n;
        left -= static_cast<size_t>(n);
      }
      if (ok && ::fsync(fd) != 0) ok = false;
      batch.clear();
      {
        std::lock_guard<std::mutex> lk(mu);
        if (!ok) io_error = true;
        synced_seq = seq;
        cv_done.notify_all();
      }
    }
  }
};

}  // namespace

extern "C" {

void* wal_open(const char* path) {
  int fd = ::open(path, O_WRONLY | O_APPEND | O_CREAT | O_CLOEXEC, 0644);
  if (fd < 0) return nullptr;
  Wal* w = new Wal();
  w->fd = fd;
  off_t sz = ::lseek(fd, 0, SEEK_END);
  w->total = sz < 0 ? 0 : static_cast<int64_t>(sz);
  w->syncer = std::thread([w] { w->run(); });
  return w;
}

int64_t wal_append(void* h, const char* buf, int64_t len, int32_t sync) {
  Wal* w = static_cast<Wal*>(h);
  uint64_t my_seq;
  int64_t total;
  {
    std::unique_lock<std::mutex> lk(w->mu);
    if (w->io_error || w->stop) return -1;
    if (len <= 0) return w->total;  // empty append must not take a ticket
    w->pending.append(buf, static_cast<size_t>(len));
    my_seq = ++w->queued_seq;
    w->total += len;
    total = w->total;
    w->cv_work.notify_one();
    if (sync) {
      w->cv_done.wait(lk, [&] { return w->synced_seq >= my_seq || w->io_error; });
      if (w->io_error) return -1;
    }
  }
  return total;
}

int64_t wal_size(void* h) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  return w->total;
}

int32_t wal_sync(void* h) {
  Wal* w = static_cast<Wal*>(h);
  std::unique_lock<std::mutex> lk(w->mu);
  uint64_t target = w->queued_seq;
  w->cv_work.notify_one();
  w->cv_done.wait(lk, [&] { return w->synced_seq >= target || w->io_error; });
  return w->io_error ? -1 : 0;
}

int32_t wal_close(void* h) {
  Wal* w = static_cast<Wal*>(h);
  {
    std::unique_lock<std::mutex> lk(w->mu);
    uint64_t target = w->queued_seq;
    w->cv_work.notify_one();
    w->cv_done.wait(lk, [&] { return w->synced_seq >= target || w->io_error; });
    w->stop = true;
    w->cv_work.notify_one();
  }
  w->syncer.join();
  int rc = w->io_error ? -1 : 0;
  if (w->fd >= 0) {
    ::fsync(w->fd);
    ::close(w->fd);
  }
  delete w;
  return rc;
}

}  // extern "C"
