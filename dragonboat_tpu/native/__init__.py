"""Native runtime components (C++, loaded via ctypes).

The only native piece this architecture needs (SURVEY.md §2: the
reference is pure Go, so there is no component list to mirror — native
code exists where OUR runtime benefits): ``walwriter`` — a group-commit
WAL appender whose write+fsync runs on a dedicated native thread with
the GIL released, coalescing concurrent workers' batches into single
fsyncs.

The shared library is compiled on first use with g++ (cached next to
the source); every consumer must handle ``load_walwriter()`` returning
None and fall back to the pure-Python path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from ..logger import get_logger

_log = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "walwriter.cpp")
_LIB = os.path.join(_HERE, "libwalwriter.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    # compile to a per-process temp path and rename: concurrent builders
    # (two processes constructing TanLogDB) must never load a
    # half-written .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-o", tmp, _SRC]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        _log.warning("native walwriter build failed to run: %s", e)
        return False
    if proc.returncode != 0:
        _log.warning("native walwriter build failed:\n%s", proc.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    os.replace(tmp, _LIB)
    return True


def load_walwriter() -> Optional[ctypes.CDLL]:
    """The walwriter library, building it on first use; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _log.warning("native walwriter load failed: %s", e)
            _load_failed = True
            return None
        lib.wal_open.argtypes = [ctypes.c_char_p]
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int32,
        ]
        lib.wal_append.restype = ctypes.c_int64
        lib.wal_size.argtypes = [ctypes.c_void_p]
        lib.wal_size.restype = ctypes.c_int64
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_sync.restype = ctypes.c_int32
        lib.wal_close.argtypes = [ctypes.c_void_p]
        lib.wal_close.restype = ctypes.c_int32
        _lib = lib
        return _lib


class NativeWalWriter:
    """ctypes handle over one WAL segment file (append-only).

    ``append(data, sync=True)`` returns the total appended bytes once
    the data is durable (group-committed with concurrent appenders).
    """

    def __init__(self, path: str):
        lib = load_walwriter()
        if lib is None:
            raise OSError("native walwriter unavailable")
        self._lib = lib
        self._h = lib.wal_open(path.encode("utf-8"))
        if not self._h:
            raise OSError(f"wal_open failed: {path}")

    def append(self, data: bytes, sync: bool = True) -> int:
        if not self._h:
            raise OSError("walwriter is closed")
        if not data:  # zero-length appends must not consume a ticket
            return self.size()
        n = self._lib.wal_append(self._h, data, len(data), int(sync))
        if n < 0:
            raise OSError("wal_append I/O error")
        return n

    def size(self) -> int:
        if not self._h:
            raise OSError("walwriter is closed")
        return self._lib.wal_size(self._h)

    def sync(self) -> None:
        if not self._h:
            raise OSError("walwriter is closed")
        if self._lib.wal_sync(self._h) != 0:
            raise OSError("wal_sync I/O error")

    def close(self) -> None:
        if self._h:
            rc = self._lib.wal_close(self._h)
            self._h = None
            if rc != 0:
                raise OSError("wal_close I/O error")

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
