"""Snapshot-stream pacing: a shared token bucket + a cap feedback loop.

reference: dragonboat's MaxSnapshotSendBytesPerSecond [U], upgraded for
the big-state plane: the cap is ONE bucket shared by every concurrent
stream job of a host (the old per-stream deficit pacing let N parallel
catch-ups each take the full rate — N laggards multiplied the cap), and
the rate is runtime-adjustable so a feedback loop can trade catch-up
speed against commit-path latency (``CapFeedback``, the LatencyBudget
discipline applied to background bandwidth).

Deliberately stdlib-only: the transport layer imports this at module
load and must not drag the storage/rsm stack with it.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class TokenBucket:
    """Byte-rate limiter shared by concurrent snapshot stream jobs.

    Tokens accrue at ``rate`` bytes/second up to ``burst_seconds`` of
    headroom (idle time banks at most one burst — a stream that paused
    must not slam the wire to "catch up" on banked credit).  Debt is
    never forgiven: a chunk larger than one burst drives the balance
    negative and the next ``throttle`` sleeps it off, so the long-run
    average respects the cap exactly.

    ``throttle(n)`` is the one call sites use: charge ``n`` bytes, sleep
    until the balance clears, return the seconds slept (the
    ``snapshot_stream_throttle_seconds_total`` metric).  Sleeps are
    sliced so ``should_abort`` (transport close) interrupts promptly.
    ``set_rate`` retunes a LIVE bucket — the cap feedback loop adjusts
    mid-stream without tearing transfers down.
    """

    def __init__(self, rate: float, burst_seconds: float = 0.1):
        if rate <= 0:
            raise ValueError(f"rate must be positive, got {rate}")
        self._lock = threading.Lock()
        self._rate = float(rate)  # guarded-by: _lock
        self._burst_s = float(burst_seconds)
        self._tokens = 0.0  # byte balance; negative = debt; guarded-by: _lock
        self._last = time.monotonic()  # guarded-by: _lock
        self.throttled_seconds = 0.0  # cumulative sleep (metrics scrape)

    @property
    def rate(self) -> float:
        # raftlint: ignore[guarded-by] scrape-time float read (GIL-atomic)
        return self._rate

    def set_rate(self, rate: float) -> None:
        if rate <= 0:
            raise ValueError(f"rate must be positive, got {rate}")
        with self._lock:
            # re-clock at the old rate first so the accrued credit/debt
            # reflects time actually spent at that rate
            self._accrue_locked()
            self._rate = float(rate)

    def _accrue_locked(self) -> None:  # guarded-by: _lock
        now = time.monotonic()
        self._tokens = min(
            self._tokens + (now - self._last) * self._rate,
            self._burst_s * self._rate,
        )
        self._last = now

    def _charge(self, nbytes: int) -> float:
        """Charge and return the seconds until the balance clears."""
        with self._lock:
            self._accrue_locked()
            self._tokens -= nbytes
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self._rate

    def throttle(
        self, nbytes: int, should_abort: Optional[Callable[[], bool]] = None
    ) -> float:
        slept = 0.0
        wait = self._charge(nbytes)
        while wait > 0:
            if should_abort is not None and should_abort():
                break
            step = min(wait, 0.05)
            time.sleep(step)
            slept += step
            with self._lock:
                self._accrue_locked()
                wait = (
                    -self._tokens / self._rate if self._tokens < 0 else 0.0
                )
        if slept:
            with self._lock:
                self.throttled_seconds += slept
        return slept


class CapFeedback:
    """Shrink the stream cap when the commit path degrades; recover when
    it is healthy — the ``LatencyBudget`` discipline applied to
    background bandwidth (docs/BIGSTATE.md "cap feedback").

    The loop owner (bench harness, an operator thread, a future engine
    hook) feeds commit latencies via ``observe`` — typically by sharing
    the same ``client.LatencyBudget`` the proposers already feed — and
    calls ``tick()`` periodically:

    * observed p99 above ``target_p99``  -> multiplicative decrease
      (``shrink``x, floored at ``floor_rate``): catch-up yields to the
      commit path immediately;
    * p99 at/below target               -> multiplicative recovery
      (``grow``x, capped at ``base_rate``): the cap creeps back so a
      transient stall doesn't strand the laggard at the floor.

    AIMD keeps it stable: decrease is fast, recovery is geometric but
    capped, and the floor guarantees catch-up always progresses.
    """

    def __init__(
        self,
        bucket: TokenBucket,
        *,
        base_rate: float,
        target_p99: float,
        floor_rate: Optional[float] = None,
        shrink: float = 0.5,
        grow: float = 1.25,
        budget=None,
        window: int = 128,
    ):
        if not (0 < shrink < 1 < grow):
            raise ValueError(f"need 0 < shrink < 1 < grow, got {shrink}/{grow}")
        self.bucket = bucket
        self.base_rate = float(base_rate)
        self.floor_rate = float(floor_rate or base_rate / 16.0)
        self.target_p99 = float(target_p99)
        self.shrink = shrink
        self.grow = grow
        # either a shared client.LatencyBudget (duck-typed: .p99()) or
        # the internal window fed through observe()
        self._budget = budget
        self._lock = threading.Lock()
        self._lat: list = []  # guarded-by: _lock
        self._window = window
        self.adjustments = 0  # rate changes applied (observability)

    def observe(self, secs: float) -> None:
        with self._lock:
            self._lat.append(secs)
            if len(self._lat) > self._window:
                del self._lat[: -self._window]

    def _p99(self) -> Optional[float]:
        if self._budget is not None:
            try:
                return self._budget.p99()
            except Exception:  # noqa: BLE001 — budget without samples
                return None
        with self._lock:
            lat = list(self._lat)
        if not lat:
            return None
        lat.sort()
        return lat[min(len(lat) - 1, int(len(lat) * 0.99))]

    def tick(self) -> float:
        """One control step; returns the (possibly adjusted) rate."""
        p99 = self._p99()
        rate = self.bucket.rate
        if p99 is None:
            return rate
        if p99 > self.target_p99:
            new = max(self.floor_rate, rate * self.shrink)
        else:
            new = min(self.base_rate, rate * self.grow)
        if new != rate:
            self.bucket.set_rate(new)
            self.adjustments += 1
        return new
