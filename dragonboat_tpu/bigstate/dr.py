"""Portable snapshot archives — the disaster-recovery interchange.

reference: tools/import.go (ImportSnapshot) and the exported-snapshot
flow of SyncRequestSnapshot [U].  The scenario: a shard has lost its
quorum permanently.  An archive exported from a surviving replica is
imported on fresh hosts with a REWRITTEN membership, and the shard
restarts from the snapshot with the new member set.

Archive layout (one directory):

    MANIFEST.json    self-describing metadata (pb.SnapshotManifest):
                     shard/replica/index/term/membership, the v2
                     container's compression, and per-file size +
                     sha256 + per-chunk crc32 list
    META             wire-encoded pb.Snapshot (legacy compat: archives
                     written here import on pre-manifest trees and
                     vice versa)
    snapshot.bin     the v2 snapshot container, verbatim
    external-*       ISnapshotFileCollection files, verbatim

Everything streams: export reads the container in ``chunk_size`` slices
(checksumming as it copies), import verifies the same slices before the
logdb is touched — a GB-scale archive never materializes in memory on
either side, and corruption is localized to a chunk index.
"""
from __future__ import annotations

import hashlib
import json
import os
import zlib
from typing import Dict, Optional, Tuple

from .. import settings
from ..pb import ManifestFile, Membership, Snapshot, SnapshotManifest
from ..pb import CompressionType

MANIFEST_FILENAME = "MANIFEST.json"
META_FILENAME = "META"
PAYLOAD_FILENAME = "snapshot.bin"


class ArchiveError(IOError, ValueError):
    """Malformed / corrupt / mismatched snapshot archive.

    Subclasses BOTH IOError and ValueError: the pre-manifest tools.py
    raised IOError for corruption and ValueError for shard mismatch,
    and existing callers catch either — the unified error must stay
    catchable through both legacy styles."""


# ---------------------------------------------------------------------------
# manifest (de)serialization
# ---------------------------------------------------------------------------
def _membership_to_json(m: Membership) -> dict:
    return {
        "config_change_id": m.config_change_id,
        "addresses": {str(k): v for k, v in m.addresses.items()},
        "non_votings": {str(k): v for k, v in m.non_votings.items()},
        "witnesses": {str(k): v for k, v in m.witnesses.items()},
        "removed": sorted(int(k) for k in m.removed),
    }


def _membership_from_json(d: dict) -> Membership:
    return Membership(
        config_change_id=int(d.get("config_change_id", 0)),
        addresses={int(k): v for k, v in d.get("addresses", {}).items()},
        non_votings={int(k): v for k, v in d.get("non_votings", {}).items()},
        witnesses={int(k): v for k, v in d.get("witnesses", {}).items()},
        removed={int(k): True for k in d.get("removed", ())},
    )


def manifest_to_json(m: SnapshotManifest) -> str:
    return json.dumps(
        {
            "format_version": m.format_version,
            "shard_id": m.shard_id,
            "replica_id": m.replica_id,
            "index": m.index,
            "term": m.term,
            "on_disk": m.on_disk,
            "chunk_size": m.chunk_size,
            "compression": int(m.compression),
            "membership": _membership_to_json(m.membership),
            "files": [
                {
                    "name": f.name,
                    "size": f.size,
                    "sha256": f.sha256,
                    "chunk_crcs": list(f.chunk_crcs),
                }
                for f in m.files
            ],
        },
        indent=2,
        sort_keys=True,
    )


def manifest_from_json(text: str) -> SnapshotManifest:
    try:
        d = json.loads(text)
    except ValueError as e:
        raise ArchiveError(f"manifest is not valid JSON: {e}")
    try:
        ver = int(d.get("format_version", 0))
        if ver != 1:
            raise ArchiveError(f"unsupported manifest format_version {ver}")
        return SnapshotManifest(
            format_version=ver,
            shard_id=int(d["shard_id"]),
            replica_id=int(d["replica_id"]),
            index=int(d["index"]),
            term=int(d["term"]),
            on_disk=bool(d.get("on_disk", False)),
            chunk_size=int(d["chunk_size"]),
            compression=CompressionType(int(d.get("compression", 0))),
            membership=_membership_from_json(d.get("membership", {})),
            files=tuple(
                ManifestFile(
                    name=f["name"],
                    size=int(f["size"]),
                    sha256=f["sha256"],
                    chunk_crcs=tuple(int(c) for c in f["chunk_crcs"]),
                )
                for f in d.get("files", ())
            ),
        )
    except ArchiveError:
        raise
    except (KeyError, TypeError, AttributeError, ValueError) as e:
        # a structurally malformed manifest (missing key, wrong shape —
        # a version-skewed or hand-edited archive) must surface through
        # the module's error contract, not a raw KeyError out of the
        # disaster-recovery import path
        raise ArchiveError(f"malformed manifest: {e!r}")


# ---------------------------------------------------------------------------
# streamed copy + checksum plumbing
# ---------------------------------------------------------------------------
def _copy_checksummed(
    src, dst_path: Optional[str], chunk_size: int
) -> Tuple[int, str, Tuple[int, ...]]:
    """Stream ``src`` (a readable file object) to ``dst_path`` (or just
    walk it when None), returning (size, sha256_hex, per-chunk crc32s).
    Bounded memory: one ``chunk_size`` slice in flight."""
    sha = hashlib.sha256()
    crcs = []
    size = 0
    out = open(dst_path, "wb") if dst_path is not None else None
    try:
        while True:
            piece = src.read(chunk_size)
            if not piece:
                break
            sha.update(piece)
            crcs.append(zlib.crc32(piece))
            size += len(piece)
            if out is not None:
                out.write(piece)
        if out is not None:
            out.flush()
            os.fsync(out.fileno())
    finally:
        if out is not None:
            out.close()
    return size, sha.hexdigest(), tuple(crcs)


def _verify_file(path: str, mf: ManifestFile, chunk_size: int) -> None:
    """Walk one archive file against its manifest record; bounded
    memory, corruption localized to a chunk index."""
    if not os.path.exists(path):
        raise ArchiveError(f"archive is missing {mf.name!r}")
    sha = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        for i, want in enumerate(mf.chunk_crcs):
            piece = f.read(chunk_size)
            if zlib.crc32(piece) != want:
                raise ArchiveError(
                    f"{mf.name!r}: chunk {i} checksum mismatch "
                    f"(archive corrupt at byte ~{i * chunk_size})"
                )
            sha.update(piece)
            size += len(piece)
        if f.read(1):
            raise ArchiveError(f"{mf.name!r}: trailing bytes past manifest")
    if size != mf.size:
        raise ArchiveError(
            f"{mf.name!r}: size {size} != manifest {mf.size}"
        )
    if sha.hexdigest() != mf.sha256:
        raise ArchiveError(f"{mf.name!r}: sha256 mismatch")


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------
def write_archive(
    storage, ss: Snapshot, export_dir: str, chunk_size: int = 0
) -> SnapshotManifest:
    """Stream the snapshot ``ss`` out of ``storage`` into a portable
    archive at ``export_dir``; returns the manifest.  Holds a storage
    GC lease for the duration so compaction cannot delete the snapshot
    dir mid-copy."""
    from ..storage.snapshotio import SnapshotReader
    from ..transport.wire import encode_snapshot_meta

    size = chunk_size or settings.Soft.snapshot_chunk_size
    os.makedirs(export_dir, exist_ok=True)
    files = []
    with storage.lease(ss.filepath):
        with storage.open_read(ss.filepath) as f:
            reader = SnapshotReader(f)  # validates meta + table sections
            externals = reader.external_files
            f.seek(0)
            n, sha, crcs = _copy_checksummed(
                f, os.path.join(export_dir, PAYLOAD_FILENAME), size
            )
        files.append(
            ManifestFile(
                name=PAYLOAD_FILENAME, size=n, sha256=sha, chunk_crcs=crcs
            )
        )
        for sf in externals:
            src = storage.external_path(ss.filepath, sf.filepath)
            with open(src, "rb") as ef:
                n, sha, crcs = _copy_checksummed(
                    ef, os.path.join(export_dir, sf.filepath), size
                )
            files.append(
                ManifestFile(
                    name=sf.filepath, size=n, sha256=sha, chunk_crcs=crcs
                )
            )
    manifest = SnapshotManifest(
        shard_id=ss.shard_id,
        replica_id=ss.replica_id,
        index=ss.index,
        term=ss.term,
        on_disk=reader.on_disk,
        chunk_size=size,
        compression=ss.compression,
        membership=ss.membership.copy(),
        files=tuple(files),
    )
    with open(os.path.join(export_dir, MANIFEST_FILENAME), "w") as f:
        f.write(manifest_to_json(manifest))
        f.flush()
        os.fsync(f.fileno())
    # legacy compat: pre-manifest import code reads META
    with open(os.path.join(export_dir, META_FILENAME), "wb") as f:
        f.write(encode_snapshot_meta(ss))
        f.flush()
        os.fsync(f.fileno())
    return manifest


# ---------------------------------------------------------------------------
# import
# ---------------------------------------------------------------------------
def read_manifest(export_dir: str) -> Optional[SnapshotManifest]:
    path = os.path.join(export_dir, MANIFEST_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, "r") as f:
        # raftlint: ignore[stream-read] bounded metadata blob (~12 B/chunk)
        return manifest_from_json(f.read())


def verify_archive(export_dir: str) -> SnapshotManifest:
    """Checksum-walk every archive file against the manifest (bounded
    memory); raises ArchiveError with the corrupt chunk localized."""
    manifest = read_manifest(export_dir)
    if manifest is None:
        raise ArchiveError(f"no {MANIFEST_FILENAME} in {export_dir}")
    for mf in manifest.files:
        _verify_file(
            os.path.join(export_dir, os.path.basename(mf.name)),
            mf,
            manifest.chunk_size,
        )
    return manifest


def import_archive(
    nodehost,
    export_dir: str,
    shard_id: int,
    replica_id: int,
    members: Dict[int, str],
) -> Snapshot:
    """Seed ``nodehost`` with the archive under a rewritten membership,
    BEFORE start_replica for the shard (NodeHost.import_snapshot).

    Verification layers, all streamed: (1) manifest per-chunk crc32 +
    sha256 of every file (when a manifest is present — legacy META-only
    exports skip to (2)); (2) the v2 container's own per-section/block
    CRC walk; (3) external files present and sized per the container's
    table.  Only then is the payload copied into local snapshot storage
    and the logdb seeded."""
    from ..storage.snapshotio import SnapshotCorruptError, SnapshotReader
    from ..transport.wire import decode_snapshot_meta

    if replica_id not in members:
        raise ValueError(f"replica {replica_id} not in new membership")

    manifest = read_manifest(export_dir)
    if manifest is not None:
        if manifest.shard_id != shard_id:
            raise ArchiveError(
                f"archive is for shard {manifest.shard_id}, not {shard_id}"
            )
        for mf in manifest.files:
            _verify_file(
                os.path.join(export_dir, os.path.basename(mf.name)),
                mf,
                manifest.chunk_size,
            )
        index, term = manifest.index, manifest.term
        old_ccid = manifest.membership.config_change_id
        compression = manifest.compression
    else:
        # legacy export (META only): identity from the wire-encoded meta
        meta_path = os.path.join(export_dir, META_FILENAME)
        if not os.path.exists(meta_path):
            raise ArchiveError(
                f"{export_dir} has neither {MANIFEST_FILENAME} nor "
                f"{META_FILENAME}"
            )
        with open(meta_path, "rb") as f:
            # raftlint: ignore[stream-read] bounded metadata blob
            meta = decode_snapshot_meta(f.read())
        if meta.shard_id != shard_id:
            raise ArchiveError(
                f"archive is for shard {meta.shard_id}, not {shard_id}"
            )
        index, term = meta.index, meta.term
        old_ccid = meta.membership.config_change_id
        compression = meta.compression

    payload_path = os.path.join(export_dir, PAYLOAD_FILENAME)
    try:
        with open(payload_path, "rb") as f:
            reader = SnapshotReader(f)
            reader.validate()  # walks every sm block (bounded memory)
            externals = reader.external_files
    except FileNotFoundError:
        raise ArchiveError(f"{export_dir} is missing {PAYLOAD_FILENAME}")
    except SnapshotCorruptError as e:
        raise ArchiveError(f"corrupt snapshot container in {export_dir}: {e}")
    for sf in externals:
        if not os.path.exists(os.path.join(export_dir, sf.filepath)):
            raise ArchiveError(
                f"archive is missing external file {sf.filepath!r}"
            )

    storage = nodehost.snapshot_storage
    csize = (
        manifest.chunk_size if manifest is not None
        else settings.Soft.snapshot_chunk_size
    )

    def build(out, _copy_fn):
        with open(payload_path, "rb") as f:
            while True:
                piece = f.read(csize)
                if not piece:
                    break
                out.write(piece)

    path, _ = storage.save_stream(
        shard_id, replica_id, index, build, suffix="imported"
    )
    for sf in externals:
        with open(os.path.join(export_dir, sf.filepath), "rb") as src:
            _copy_checksummed(
                src, storage.external_path(path, sf.filepath), csize
            )

    new_membership = Membership(
        config_change_id=old_ccid + 1,
        addresses=dict(members),
    )
    ss = Snapshot(
        filepath=path,
        file_size=storage.file_size(path),
        index=index,
        term=term,
        membership=new_membership,
        shard_id=shard_id,
        replica_id=replica_id,
        imported=True,
        compression=compression,
    )
    nodehost.logdb.import_snapshot(ss, replica_id)
    return ss
