"""Big-state plane: on-disk state machines, streaming snapshot
delivery, and disaster-recovery export/import (docs/BIGSTATE.md).

Submodules (imported lazily — the transport layer pulls ``pacing`` at
module load and must not drag the storage/rsm stack in with it):

* :mod:`.pacing`  — ``TokenBucket`` (the shared snapshot-stream
  bandwidth cap) and ``CapFeedback`` (the LatencyBudget-style loop that
  shrinks the cap when the commit path degrades).
* :mod:`.ondisk`  — ``OnDiskKV``, the reference ``IOnDiskStateMachine``
  over ``storage/vfs`` (WAL + checkpoint, applied-index persistence,
  crash-consistent tail replay).
* :mod:`.dr`      — portable snapshot archives with a self-describing
  manifest; the ``NodeHost.export_snapshot``/``import_snapshot`` core.
"""
from __future__ import annotations

import os

from .pacing import CapFeedback, TokenBucket

__all__ = ["CapFeedback", "TokenBucket", "gb_tier"]


def gb_tier() -> bool:
    """True when the operator armed the GB-scale big-state tier
    (``DRAGONBOAT_BIGSTATE_GB=1``): the slow catch-up tests and the
    full production-day soak (docs/SCENARIO.md) then size their on-disk
    shard near a gigabyte and keep streams capped; everything else
    stays at the MB-scale default."""
    return os.environ.get("DRAGONBOAT_BIGSTATE_GB", "0") not in ("", "0")


def __getattr__(name):
    # lazy: `from dragonboat_tpu.bigstate import ondisk / dr` works
    # without making transport -> pacing imports pull the full stack
    if name in ("ondisk", "dr"):
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
