"""OnDiskKV — the reference ``IOnDiskStateMachine`` over ``storage/vfs``.

reference: statemachine/ondisk.go contract + the ondisk example's
pebble-backed KV [U].  The contract this implementation demonstrates
end to end (docs/BIGSTATE.md "On-disk state machines"):

* the SM owns its own durable storage (a checkpoint + WAL pair under
  one directory, written through ``storage/vfs`` so the strict-crash
  MemFS tests apply);
* ``open()`` recovers local state and reports the APPLIED INDEX it
  recovered to — raft then replays only the log suffix past it (the
  ``e.index <= last_applied`` skip in rsm/statemachine.py);
* ``update()`` appends to the WAL as it applies (in-core dict is the
  working set; the WAL tail is pending until ``sync``), and folds the
  WAL into a fresh checkpoint once it outgrows ``compact_wal_bytes`` —
  amortized on the apply path, LSM-style;
* ``sync()`` makes everything applied so far durable (one fsync,
  deliberately O(1): the rsm calls it in its apply-exclusive section
  before every snapshot point);
* ``save_snapshot``/``recover_from_snapshot`` stream record-by-record
  with bounded memory — a GB-scale state never materializes beyond the
  working set, and recovery leaves DURABLE state (fresh checkpoint,
  empty WAL) before raft resets the log.

Crash consistency: the checkpoint is written to ``base.kv.tmp``,
fsynced, renamed over ``base.kv`` and the directory fsynced — the
rename is the commit point.  WAL frames are CRC-framed; replay stops at
the first torn/corrupt frame and truncates it away (a torn final write
is exactly what ``StrictMemFS.crash()`` produces).  Replay SKIPS frames
at or below the checkpoint's applied index — the "replay only the WAL
suffix past the persisted index" discipline, pinned by
tests/test_bigstate.py.

Command codec (struct-framed, not pickle — commands travel the wire and
the library-wide no-pickle guard applies): ``put_cmd``/``del_cmd``.
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from ..pb import MASK64
from ..statemachine import IOnDiskStateMachine, Result, SnapshotStopped
from ..storage import vfs as vfs_mod

BASE_FILENAME = "base.kv"
WAL_FILENAME = "wal.log"

_MAGIC = 0x4B444B56  # "VKDK"
_BASE_VERSION = 1
_u32 = struct.Struct("<I")
_u64 = struct.Struct("<Q")
_frame_hdr = struct.Struct("<II")  # payload len, crc32

OP_PUT = 1
OP_DEL = 2

# default WAL size past which sync() folds it into a fresh checkpoint
DEFAULT_COMPACT_WAL_BYTES = 32 * 1024 * 1024
_READ_SLICE = 1 << 20  # bounded read unit for replay/recovery


def put_cmd(key: bytes, value: bytes) -> bytes:
    """The OnDiskKV write command (op, klen, key, value)."""
    return b"".join(
        (bytes([OP_PUT]), _u32.pack(len(key)), key, value)
    )


def del_cmd(key: bytes) -> bytes:
    return b"".join((bytes([OP_DEL]), _u32.pack(len(key)), key))


def decode_cmd(cmd: bytes) -> Tuple[int, bytes, bytes]:
    """(op, key, value); raises ValueError on a malformed command."""
    if len(cmd) < 5:
        raise ValueError("OnDiskKV: short command")
    op = cmd[0]
    (klen,) = _u32.unpack_from(cmd, 1)
    if op not in (OP_PUT, OP_DEL) or len(cmd) < 5 + klen:
        raise ValueError(f"OnDiskKV: malformed command (op={op})")
    key = cmd[1 + 4: 5 + klen]
    return op, key, cmd[5 + klen:]


class _BoundedReader:
    """Incremental reads over a seekable vfs handle with its own
    buffer — WAL/checkpoint replay touches one slice at a time."""

    def __init__(self, f):
        self._f = f
        self._buf = b""
        self._off = 0  # consumed bytes (absolute)

    def exactly(self, n: int) -> Optional[bytes]:
        """n bytes, or None at a clean EOF boundary; short tail data
        (a torn frame) also returns None — callers treat both as end."""
        while len(self._buf) < n:
            piece = self._f.read(_READ_SLICE)
            if not piece:
                return None
            self._buf += piece
        out, self._buf = self._buf[:n], self._buf[n:]
        self._off += n
        return out

    @property
    def consumed(self) -> int:
        return self._off


class OnDiskKV(IOnDiskStateMachine):
    """Durable KV state machine (see module docstring).

    ``base_dir`` is this replica's private directory; ``fs`` any
    :class:`storage.vfs.IVFS` (StrictMemFS in crash tests).  The
    in-core dict is the working set — lookups never touch disk.
    """

    def __init__(
        self,
        shard_id: int,
        replica_id: int,
        base_dir: Optional[str] = None,
        fs: Optional[vfs_mod.IVFS] = None,
        compact_wal_bytes: int = DEFAULT_COMPACT_WAL_BYTES,
    ):
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.fs = fs or vfs_mod.DEFAULT
        self.dir = base_dir or os.path.join(
            "/tmp", "tpu-raft-ondiskkv", f"{shard_id}-{replica_id}"
        )
        self.compact_wal_bytes = compact_wal_bytes
        self._data: Dict[bytes, bytes] = {}
        self.applied = 0  # highest index applied to the in-core state
        self._wal = None  # open append handle
        self._wal_bytes = 0  # bytes in the current WAL (incl. unsynced)
        self._bytes = 0  # sum of key+value bytes (the "state size" probe)
        # serializes checkpoint rewrites against close(); update/sync
        # run on the one apply worker and need no lock among themselves
        self._io_lock = threading.Lock()
        # observability for tests/bench
        self.stats = {
            "opens": 0, "replayed": 0, "skipped": 0, "torn": 0,
            "checkpoints": 0, "syncs": 0,
        }

    # -- paths ----------------------------------------------------------
    @property
    def _base_path(self) -> str:
        return os.path.join(self.dir, BASE_FILENAME)

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.dir, WAL_FILENAME)

    # -- lifecycle ------------------------------------------------------
    def open(self, stopc) -> int:
        """Recover checkpoint + WAL suffix; report the applied index."""
        self.stats["opens"] += 1
        self.fs.makedirs(self.dir)
        parent = os.path.dirname(self.dir.rstrip("/"))
        if parent:
            try:
                self.fs.sync_dir(parent)  # make our own dir's creation durable
            except (OSError, FileNotFoundError):  # relative/odd roots:
                pass  # best-effort — makedirs itself is the contract
        self._data = {}
        self._bytes = 0
        self.applied = 0
        if self.fs.exists(self._base_path):
            self._load_checkpoint()
        self._replay_wal()
        self._wal = self.fs.open_append(self._wal_path)
        self._wal_bytes = self.fs.stat_size(self._wal_path)
        return self.applied

    def _load_checkpoint(self) -> None:
        f = self.fs.open_read(self._base_path)
        try:
            r = _BoundedReader(f)
            hdr = r.exactly(4 + 1 + _u64.size + _u64.size)
            if hdr is None or _u32.unpack_from(hdr, 0)[0] != _MAGIC:
                raise IOError(f"{self._base_path}: bad checkpoint header")
            if hdr[4] != _BASE_VERSION:
                raise IOError(
                    f"{self._base_path}: unsupported version {hdr[4]}"
                )
            (applied,) = _u64.unpack_from(hdr, 5)
            (count,) = _u64.unpack_from(hdr, 13)
            for _ in range(count):
                rec = self._read_record(r)
                if rec is None:
                    raise IOError(
                        f"{self._base_path}: truncated checkpoint "
                        f"(expected {count} records)"
                    )
                k, v = rec
                self._data[k] = v
                self._bytes += len(k) + len(v)
            self.applied = applied
        finally:
            f.close()

    @staticmethod
    def _read_record(r: _BoundedReader) -> Optional[Tuple[bytes, bytes]]:
        hdr = r.exactly(_frame_hdr.size)
        if hdr is None:
            return None
        ln, crc = _frame_hdr.unpack(hdr)
        body = r.exactly(ln)
        if body is None or zlib.crc32(body) != crc:
            raise IOError("checkpoint record corrupt")
        (klen,) = _u32.unpack_from(body, 0)
        return body[4: 4 + klen], body[4 + klen:]

    def _replay_wal(self) -> None:
        """Apply the WAL suffix past the checkpoint's applied index;
        truncate away a torn tail (crash mid-append)."""
        if not self.fs.exists(self._wal_path):
            return
        f = self.fs.open_read(self._wal_path)
        try:
            r = _BoundedReader(f)
            good = 0  # offset past the last intact frame
            while True:
                hdr = r.exactly(_frame_hdr.size)
                if hdr is None:
                    break
                ln, crc = _frame_hdr.unpack(hdr)
                body = r.exactly(ln)
                if body is None or zlib.crc32(body) != crc:
                    self.stats["torn"] += 1
                    break
                good = r.consumed
                (index,) = _u64.unpack_from(body, 0)
                if index <= self.applied:
                    # at/below the persisted index: the checkpoint (or a
                    # replayed predecessor) already holds this write —
                    # the replay-only-the-suffix discipline
                    self.stats["skipped"] += 1
                    continue
                self._apply_cmd(body[8:])
                self.applied = index
                self.stats["replayed"] += 1
        finally:
            f.close()
        if good < self.fs.stat_size(self._wal_path):
            # drop the torn/corrupt tail so the reopened append handle
            # never interleaves fresh frames with garbage
            self.fs.truncate(self._wal_path, good)

    def _apply_cmd(self, cmd: bytes) -> Result:
        try:
            op, k, v = decode_cmd(cmd)
        except ValueError:
            return Result(value=0)
        if op == OP_PUT:
            old = self._data.get(k)
            if old is not None:
                self._bytes -= len(k) + len(old)
            self._data[k] = v
            self._bytes += len(k) + len(v)
            return Result(value=1)
        old = self._data.pop(k, None)
        if old is not None:
            self._bytes -= len(k) + len(old)
        return Result(value=1 if old is not None else 0)

    # -- apply path (one apply worker) ----------------------------------
    def update(self, entries: List) -> List:
        if self._wal is None:
            raise RuntimeError("OnDiskKV.update before open()")
        for e in entries:
            body = _u64.pack(e.index & MASK64) + e.cmd
            frame = _frame_hdr.pack(len(body), zlib.crc32(body)) + body
            self._wal.write(frame)
            self._wal_bytes += len(frame)
            e.result = self._apply_cmd(e.cmd)
            self.applied = e.index
        if self._wal_bytes >= self.compact_wal_bytes:
            # fold the WAL into a fresh checkpoint HERE, on the apply
            # path that generated the bytes (amortized, LSM-style), NOT
            # in sync(): the rsm calls sync() inside its apply-exclusive
            # section right before every snapshot, and an O(state)
            # rewrite there would stall all applies for the duration
            # (review finding).  The checkpoint is durable on its own
            # (tmp -> fsync -> rename -> dir fsync), so folding
            # not-yet-synced frames is safe — it only ever makes MORE
            # applied state durable.
            with self._io_lock:
                self._write_checkpoint(self.applied, self._data.items())
                self._reset_wal()
        return entries

    def lookup(self, query):
        # tuple OR list: RPC queries ride the JSON value lane, which
        # turns ("get", k) into ["get", k] (transport/wire.py contract)
        if (
            isinstance(query, (tuple, list))
            and len(query) == 2
            and query[0] == "get"
        ):
            query = query[1]
        if query == ("stats",) or query == ["stats"]:
            return {
                "applied": self.applied,
                "keys": len(self._data),
                "bytes": self._bytes,
                **self.stats,
            }
        return self._data.get(query)

    def sync(self) -> None:
        """One fsync makes every applied entry durable.  Deliberately
        O(1): the rsm calls this inside its apply-exclusive section
        before fixing every snapshot point, so the WAL->checkpoint fold
        lives on the update() path instead (amortized per
        ``compact_wal_bytes`` of writes)."""
        self.stats["syncs"] += 1
        self._wal.sync()

    def _write_checkpoint(self, applied: int, items) -> None:
        """Atomic checkpoint rewrite: tmp -> fsync -> rename -> dir
        fsync (the commit point)."""
        seq = items if hasattr(items, "__len__") else list(items)
        count = len(seq)

        def all_chunks() -> Iterator[bytes]:
            yield _u32.pack(_MAGIC) + bytes([_BASE_VERSION])
            yield _u64.pack(applied & MASK64)
            yield _u64.pack(count & MASK64)
            for k, v in seq:
                body = _u32.pack(len(k)) + k + v
                yield _frame_hdr.pack(len(body), zlib.crc32(body))
                yield body

        tmp = self._base_path + ".tmp"
        self.fs.write_file_chunks(tmp, all_chunks())
        self.fs.rename(tmp, self._base_path)
        self.fs.sync_dir(self.dir)
        self.stats["checkpoints"] += 1

    def _reset_wal(self) -> None:
        """Empty the WAL after its contents landed in the checkpoint.
        Order matters: the checkpoint rename is already durable, so a
        crash between it and this truncate only leaves frames the next
        replay SKIPS (index <= checkpoint applied)."""
        if self._wal is not None:
            self._wal.close()
        self.fs.truncate(self._wal_path, 0)
        self._wal = self.fs.open_append(self._wal_path)
        self._wal_bytes = 0

    # -- snapshots ------------------------------------------------------
    def prepare_snapshot(self):
        """Point-in-time view: (applied, shallow dict copy).  Values are
        immutable bytes, so the copy is O(keys) pointers — cheap even at
        GB-scale values — and save_snapshot streams OUTSIDE the apply
        lock from this view (rsm concurrent-snapshot discipline)."""
        return self.applied, dict(self._data)

    def save_snapshot(self, ctx, w, done) -> None:
        """Stream the prepared view record-by-record (bounded memory)."""
        applied, data = ctx
        w.write(_u32.pack(_MAGIC) + bytes([_BASE_VERSION]))
        w.write(_u64.pack(applied & MASK64))
        w.write(_u64.pack(len(data)))
        i = 0
        for k, v in data.items():
            body = _u32.pack(len(k)) + k + v
            w.write(_frame_hdr.pack(len(body), zlib.crc32(body)))
            w.write(body)
            i += 1
            if (i & 0x3FF) == 0 and done.is_set():
                raise SnapshotStopped()

    def recover_from_snapshot(self, r, done) -> None:
        """Rebuild from a streamed snapshot and make it DURABLE (fresh
        checkpoint + empty WAL) before returning — raft resets the log
        to the snapshot point right after, so un-persisted recovered
        state would be unrecoverable after a crash."""
        br = _BoundedReader(r)
        hdr = br.exactly(4 + 1 + _u64.size + _u64.size)
        if hdr is None or _u32.unpack_from(hdr, 0)[0] != _MAGIC:
            raise IOError("OnDiskKV snapshot: bad header")
        if hdr[4] != _BASE_VERSION:
            raise IOError(f"OnDiskKV snapshot: unsupported version {hdr[4]}")
        (applied,) = _u64.unpack_from(hdr, 5)
        (count,) = _u64.unpack_from(hdr, 13)
        data: Dict[bytes, bytes] = {}
        nbytes = 0
        for i in range(count):
            rec = self._read_record(br)
            if rec is None:
                raise IOError(
                    f"OnDiskKV snapshot: truncated at record {i}/{count}"
                )
            k, v = rec
            data[k] = v
            nbytes += len(k) + len(v)
            if (i & 0x3FF) == 0 and done.is_set():
                raise SnapshotStopped()
        self._data = data
        self._bytes = nbytes
        self.applied = applied
        with self._io_lock:
            self.fs.makedirs(self.dir)
            self._write_checkpoint(applied, self._data.items())
            if self._wal is None:
                # recover before open() (imported snapshot boot path)
                self.fs.write_file_chunks(self._wal_path, ())
            self._reset_wal()

    def close(self) -> None:
        with self._io_lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None


def ondisk_kv_factory(
    root: str,
    fs: Optional[vfs_mod.IVFS] = None,
    compact_wal_bytes: int = DEFAULT_COMPACT_WAL_BYTES,
):
    """``sm_factory`` for NodeHost.start_replica: each replica gets its
    own subdirectory of ``root`` (replicas NEVER share state dirs)."""

    def factory(shard_id: int, replica_id: int) -> OnDiskKV:
        return OnDiskKV(
            shard_id,
            replica_id,
            base_dir=os.path.join(root, f"{shard_id}-{replica_id}"),
            fs=fs,
            compact_wal_bytes=compact_wal_bytes,
        )

    return factory
